"""Unified per-job timelines and the fleet goodput rollup.

The operator emits rich but scattered signals: ``status.phaseTimeline``
stamps phase entries, the scheduler emits Queued/Admitted/Preempted
events, the failure ledger records every restart with its resume step,
the startup breakdown times each stage of an attempt, the step-phase
recorder digests where step time goes, and the elastic/store blocks log
resizes, remediations and uploads. Answering "why was this job slow?"
means hand-joining five status blocks. This module joins them once:

- :class:`TimelineStore` captures the operator's *decision* events
  (the ones that flow through the event recorder) per job, each stamped
  with the reconcile trace id so a timeline entry links to the exact
  ``/api/traces`` reconcile that caused it. Per-job-keyed, so it follows
  the PR-15 lifecycle contract: witness-tracked, pruned by the
  controller's deletion reconcile through :meth:`forget_job`.
- :func:`assemble_timeline` merges the live decision stream with the
  status-derived spans (phases, ledger, startup stages, step digest,
  resizes, remediations, store uploads, profile captures) into one
  ordered span list.
- :func:`to_chrome_trace` exports that list as Chrome trace-event JSON
  (perfetto-loadable) for offline analysis.
- :func:`fleet_rollup` aggregates per-job ``status.goodput`` folds into
  the cluster view ``GET /api/fleet`` serves: cluster goodput ratio,
  per-queue wait quantiles, preemption cost in lost step-seconds, and
  straggler/remediation counts.

Everything except the store is a pure function over status dicts — the
status server calls them per request; nothing here caches derived data.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from tpu_operator.util import joblife, lockdep, tracing
from tpu_operator.util.util import now_rfc3339, parse_rfc3339

# Decision events kept per job. 256 covers hundreds of restarts/resizes;
# beyond that the oldest entries rotate out (the status-derived spans —
# ledger, phaseTimeline — are not subject to this cap).
EVENTS_PER_JOB_CAP = 256

# Chrome trace-event lanes (tid) per span kind, so perfetto renders one
# row per signal family instead of one interleaved soup.
_LANES = {
    "phase": 1,
    "decision": 2,
    "failure": 3,
    "startup": 4,
    "steps": 5,
    "elastic": 6,
    "store": 7,
    "profile": 8,
}
_LANE_NAMES = {
    1: "phases",
    2: "decisions",
    3: "failure ledger",
    4: "startup stages",
    5: "step timing",
    6: "elastic",
    7: "store",
    8: "profile",
}


class TimelineStore:
    """Bounded per-job ring of operator decision events.

    Fed by the event recorder's observer hook (every Queued / Admitted /
    Preempted / GroupRestart / ElasticResized / ... event lands here with
    the reconcile trace id attached); drained by the status server when
    assembling a timeline; pruned by the controller's deletion listener.
    """

    def __init__(self) -> None:
        self._lock = lockdep.lock("TimelineStore._lock")
        self._events: Dict[Tuple[str, str], List[Dict[str, Any]]] = \
            joblife.track("TimelineStore._events")  # per-job: forget_job; guarded-by: _lock

    def record_event(self, namespace: str, name: str, event_type: str,
                     reason: str, message: str) -> None:
        entry: Dict[str, Any] = {
            "time": now_rfc3339(),
            "type": str(event_type),
            "reason": str(reason),
            "message": str(message),
        }
        trace_id = tracing.current_trace_id()
        if trace_id:
            entry["traceId"] = trace_id
        with self._lock:
            events = self._events.setdefault((namespace, name), [])
            events.append(entry)
            if len(events) > EVENTS_PER_JOB_CAP:
                del events[:len(events) - EVENTS_PER_JOB_CAP]

    def events(self, namespace: str, name: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events.get((namespace, name), ())]

    def job_count(self) -> int:
        with self._lock:
            return len(self._events)

    def forget_job(self, namespace: str, name: str) -> None:
        """Deletion-reconcile prune (wired as a controller deletion
        listener, which runs before the joblife sweep)."""
        with self._lock:
            self._events.pop((namespace, name), None)


# --- timeline assembly -------------------------------------------------------


def _span(name: str, kind: str, start: Optional[float],
          duration: Optional[float] = None,
          attrs: Optional[Dict[str, Any]] = None,
          trace_id: str = "") -> Optional[Dict[str, Any]]:
    if start is None:
        return None
    out: Dict[str, Any] = {"name": name, "kind": kind, "start": start}
    if duration is not None:
        out["durationSeconds"] = round(max(0.0, duration), 6)
    if attrs:
        out["attrs"] = {k: v for k, v in attrs.items() if v is not None}
    if trace_id:
        out["traceId"] = trace_id
    return out


# Startup stages in pipeline order, (status key, span label). The
# breakdown records durations but not per-stage wall-clock starts, so the
# assembler lays them back-to-back ending at the breakdown's stamp time —
# a reconstruction, which the span attrs flag.
_STARTUP_STAGES = (
    ("rendezvousSeconds", "rendezvous"),
    ("restoreSeconds", "restore"),
    ("compileSeconds", "compile"),
    ("firstStepSeconds", "first-step"),
)


def _phase_spans(status: Dict[str, Any], now: float) -> List[Dict[str, Any]]:
    timeline = status.get("phaseTimeline") or {}
    entries: List[Tuple[float, str]] = []
    for phase, stamp in timeline.items():
        t = parse_rfc3339(str(stamp))
        if t is not None:
            entries.append((t, str(phase)))
    entries.sort()
    spans: List[Dict[str, Any]] = []
    terminal = status.get("phase") in ("Done", "Failed")
    for idx, (start, phase) in enumerate(entries):
        if idx + 1 < len(entries):
            duration: Optional[float] = entries[idx + 1][0] - start
        elif phase in ("Done", "Failed"):
            duration = 0.0
        elif terminal:
            duration = 0.0
        else:
            duration = max(0.0, now - start)
        sp = _span(f"phase:{phase}", "phase", start, duration,
                   {"phase": phase, "ongoing": idx + 1 == len(entries)
                    and not terminal or None})
        if sp:
            spans.append(sp)
    return spans


def _event_spans(events: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    spans = []
    for ev in events:
        start = parse_rfc3339(str(ev.get("time", "")))
        sp = _span(f"decision:{ev.get('reason', '')}", "decision", start,
                   attrs={"type": ev.get("type"),
                          "message": ev.get("message")},
                   trace_id=str(ev.get("traceId", "")))
        if sp:
            spans.append(sp)
    return spans


def _ledger_spans(status: Dict[str, Any]) -> List[Dict[str, Any]]:
    spans = []
    for rec in status.get("failures") or []:
        start = parse_rfc3339(str(rec.get("time", "")))
        sp = _span(f"restart:{rec.get('kind', '')}", "failure", start,
                   attrs={"attempt": rec.get("attempt"),
                          "reason": rec.get("reason"),
                          "resumeStep": rec.get("resumeStep"),
                          "worldSlices": rec.get("worldSlices"),
                          "lostSteps": rec.get("lostSteps")})
        if sp:
            spans.append(sp)
    return spans


def _startup_spans(status: Dict[str, Any]) -> List[Dict[str, Any]]:
    st = status.get("startup") or {}
    end = parse_rfc3339(str(st.get("time", "")))
    if end is None:
        return []
    stages = [(label, float(st.get(key) or 0.0))
              for key, label in _STARTUP_STAGES if st.get(key)]
    total = sum(d for _, d in stages)
    cursor = end - total
    spans = []
    for label, duration in stages:
        sp = _span(f"startup:{label}", "startup", cursor, duration,
                   {"attempt": st.get("attempt"), "reconstructed": True})
        if sp:
            spans.append(sp)
        cursor += duration
    return spans


def _digest_spans(status: Dict[str, Any]) -> List[Dict[str, Any]]:
    spans = []
    digest = status.get("stepTiming") or {}
    start = parse_rfc3339(str(digest.get("time", "")))
    if start is not None:
        attrs = {k: digest.get(k) for k in
                 ("p50Seconds", "p95Seconds", "maxSeconds", "steps",
                  "windowSteps", "phases") if digest.get(k) is not None}
        sp = _span("steps:digest", "steps", start, attrs=attrs)
        if sp:
            spans.append(sp)
    return spans


def _elastic_spans(status: Dict[str, Any]) -> List[Dict[str, Any]]:
    spans = []
    elastic = status.get("elastic") or {}
    start = parse_rfc3339(str(elastic.get("time", "")))
    if start is not None and elastic.get("resizes"):
        sp = _span("elastic:resize", "elastic", start,
                   attrs={"slices": elastic.get("slices"),
                          "workers": elastic.get("workers"),
                          "resizes": elastic.get("resizes"),
                          "direction": elastic.get("lastResizeDirection")})
        if sp:
            spans.append(sp)
    for rem in elastic.get("remediations") or []:
        start = parse_rfc3339(str(rem.get("time", "")))
        sp = _span(f"elastic:remediation:{rem.get('action', '')}", "elastic",
                   start, attrs={k: rem.get(k) for k in
                                 ("action", "worker", "slice", "ratio")
                                 if rem.get(k) is not None})
        if sp:
            spans.append(sp)
    return spans


def _store_spans(status: Dict[str, Any]) -> List[Dict[str, Any]]:
    spans = []
    store = status.get("store") or {}
    start = parse_rfc3339(str(store.get("time", "")))
    if start is not None:
        sp = _span("store:upload", "store", start,
                   attrs={"lastUploadedStep": store.get("lastUploadedStep"),
                          "uploadFailures": store.get("uploadFailures"),
                          "prefetched": store.get("prefetched")})
        if sp:
            spans.append(sp)
    return spans


def _profile_spans(status: Dict[str, Any]) -> List[Dict[str, Any]]:
    profile = status.get("profile") or {}
    start = parse_rfc3339(str(profile.get("time", "")))
    sp = _span(f"profile:{str(profile.get('state', '')).lower()}", "profile",
               start, attrs={"id": profile.get("id"),
                             "artifactKey": profile.get("artifactKey"),
                             "capturedSteps": profile.get("capturedSteps")})
    return [sp] if sp else []


def assemble_timeline(namespace: str, name: str, status: Dict[str, Any],
                      events: Iterable[Dict[str, Any]],
                      now: Optional[float] = None) -> Dict[str, Any]:
    """One ordered span list per job, merged from every status signal
    plus the live decision stream. Pure function: derives everything per
    call from the passed status/events."""
    now = time.time() if now is None else now
    spans: List[Dict[str, Any]] = []
    spans.extend(_phase_spans(status, now))
    spans.extend(_event_spans(events))
    spans.extend(_ledger_spans(status))
    spans.extend(_startup_spans(status))
    spans.extend(_digest_spans(status))
    spans.extend(_elastic_spans(status))
    spans.extend(_store_spans(status))
    spans.extend(_profile_spans(status))
    spans.sort(key=lambda s: (s["start"], s["kind"], s["name"]))
    out: Dict[str, Any] = {
        "job": f"{namespace}/{name}",
        "phase": status.get("phase", ""),
        "spans": spans,
    }
    scheduling = status.get("scheduling") or {}
    if scheduling:
        out["scheduling"] = {k: scheduling.get(k)
                             for k in ("queue", "priority", "position")
                             if scheduling.get(k) is not None}
    goodput = status.get("goodput") or {}
    if goodput:
        out["goodput"] = {k: goodput.get(k)
                          for k in ("ratio", "usefulStepSeconds",
                                    "wallclockSeconds", "lastStep")
                          if goodput.get(k) is not None}
    return out


def to_chrome_trace(timeline: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Chrome trace-event JSON array (perfetto's legacy JSON importer):
    duration spans become ``ph: "X"`` complete events, point-in-time
    spans become ``ph: "i"`` instants; each span kind gets its own lane
    via thread-name metadata."""
    job = timeline.get("job", "")
    out: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
         "args": {"name": job}},
    ]
    used_lanes = set()
    events: List[Dict[str, Any]] = []
    for span in timeline.get("spans") or []:
        tid = _LANES.get(str(span.get("kind")), 2)
        used_lanes.add(tid)
        ts_us = int(float(span["start"]) * 1e6)
        ev: Dict[str, Any] = {
            "name": span.get("name", ""),
            "pid": 1,
            "tid": tid,
            "ts": ts_us,
            "cat": span.get("kind", ""),
        }
        args = dict(span.get("attrs") or {})
        if span.get("traceId"):
            args["traceId"] = span["traceId"]
        if args:
            ev["args"] = args
        if "durationSeconds" in span:
            ev["ph"] = "X"
            ev["dur"] = int(float(span["durationSeconds"]) * 1e6)
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        events.append(ev)
    for tid in sorted(used_lanes):
        out.append({"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                    "args": {"name": _LANE_NAMES.get(tid, str(tid))}})
    out.extend(events)
    return out


# --- fleet rollup ------------------------------------------------------------


def quantiles(samples: List[float]) -> Dict[str, float]:
    """Nearest-rank p50/p95 over a sample list (the per-queue wait
    summary shape)."""
    if not samples:
        return {"p50": 0.0, "p95": 0.0, "count": 0}
    ordered = sorted(samples)
    n = len(ordered)

    def rank(q: float) -> float:
        idx = min(n - 1, max(0, int(q * n + 0.5) - 1))
        return ordered[idx]

    return {"p50": round(rank(0.50), 6), "p95": round(rank(0.95), 6),
            "count": n}


def fleet_rollup(jobs: List[Dict[str, Any]],
                 queue_waits: Optional[Dict[str, Dict[str, float]]] = None,
                 ) -> Dict[str, Any]:
    """Aggregate per-job status into the ``GET /api/fleet`` body.

    ``jobs`` rows are ``{"namespace", "name", "status": {...}}``. Cluster
    goodput is the fold of the per-job folds: Σ usefulStepSeconds over
    Σ wallclockSeconds, so it matches ``status.goodput`` by construction.
    Preemption cost sums the ledger's per-restart ``lostSteps`` (steps
    re-run because the durable resume step trailed the step reached at
    failure) times the job's current step time — an approximation when
    step time drifted across attempts, and flagged as such in docs.
    """
    useful = 0.0
    wallclock = 0.0
    lost_step_seconds = 0.0
    lost_steps = 0
    restarts = 0
    straggler_count = 0
    remediation_count = 0
    rows: List[Dict[str, Any]] = []
    for job in jobs:
        status = job.get("status") or {}
        goodput = status.get("goodput") or {}
        job_useful = float(goodput.get("usefulStepSeconds") or 0.0)
        job_wall = float(goodput.get("wallclockSeconds") or 0.0)
        useful += job_useful
        wallclock += job_wall
        beat = status.get("lastHeartbeat") or {}
        step_time = float(beat.get("stepTimeSeconds") or 0.0)
        failures = status.get("failures") or []
        restarts += len(failures)
        job_lost_steps = sum(int(rec.get("lostSteps") or 0)
                             for rec in failures)
        lost_steps += job_lost_steps
        lost_step_seconds += job_lost_steps * step_time
        stragglers = status.get("stragglers") or []
        straggler_count += len(stragglers)
        worst_ratio = 0.0
        for s in stragglers:
            worst_ratio = max(worst_ratio, float(s.get("ratio") or 0.0))
        elastic = status.get("elastic") or {}
        remediation_count += len(elastic.get("remediations") or [])
        checkpoint = status.get("checkpoint") or {}
        scheduling = status.get("scheduling") or {}
        rows.append({
            "namespace": job.get("namespace", ""),
            "name": job.get("name", ""),
            "phase": status.get("phase", ""),
            "queue": scheduling.get("queue", ""),
            "queuePosition": scheduling.get("position"),
            "goodputRatio": goodput.get("ratio"),
            "worstStragglerRatio": round(worst_ratio, 4) or None,
            "lastDurableStep": checkpoint.get("lastCheckpointStep"),
            "lastStep": goodput.get("lastStep", beat.get("step")),
            "restarts": len(failures),
        })
    rows.sort(key=lambda r: (r["namespace"], r["name"]))
    ratio = min(1.0, useful / wallclock) if wallclock > 0 else 0.0
    return {
        "jobs": rows,
        "goodput": {
            "usefulStepSeconds": round(useful, 3),
            "wallclockSeconds": round(wallclock, 3),
            "ratio": round(ratio, 4),
        },
        "queues": dict(queue_waits or {}),
        "preemption": {
            "restarts": restarts,
            "lostSteps": lost_steps,
            "lostStepSeconds": round(lost_step_seconds, 3),
        },
        "stragglers": {
            "flagged": straggler_count,
            "remediations": remediation_count,
        },
    }
