"""In-process Kubernetes apiserver subset for end-to-end testing.

The reference's test strategy deferred everything its client-go fakes could
not express to a real cluster it did not ship tests for (SURVEY.md §4:
DeleteCollection untestable, E2E binary missing). This module closes that
gap, playing the role of controller-runtime's *envtest*: a real HTTP server
speaking enough of the Kubernetes REST API (CRUD, status subresource,
label-selected list/deletecollection, chunked ``?watch=true`` streams) for
the operator's real REST client, informers, and leader election to run
unmodified — so the full binary path can be driven without any cluster.

State lives in a backing :class:`tpu_operator.client.fake.FakeClientset`,
which tests can also poke directly (e.g. to flip pod statuses the way
kubelet would).
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from tpu_operator.client import errors
from tpu_operator.client.fake import FakeClientset

log = logging.getLogger(__name__)

_RESOURCES = (
    "pods", "services", "events", "endpoints", "configmaps", "leases",
    "tpujobs", "nodes",
)


def _parse(path: str) -> Tuple[Optional[str], str, str, bool]:
    """path → (resource, namespace, name, is_status). Accepts both core
    (``/api/v1/...``) and group (``/apis/<g>/<v>/...``) prefixes."""
    parts = [p for p in path.split("/") if p]
    # strip prefix: ["api","v1"] or ["apis",group,version]
    if parts[:1] == ["api"]:
        parts = parts[2:]
    elif parts[:1] == ["apis"]:
        parts = parts[3:]
    else:
        return None, "", "", False
    namespace = ""
    if parts[:1] == ["namespaces"] and len(parts) >= 2:
        namespace = parts[1]
        parts = parts[2:]
    if not parts or parts[0] not in _RESOURCES:
        return None, "", "", False
    resource = parts[0]
    name = parts[1] if len(parts) > 1 else ""
    is_status = len(parts) > 2 and parts[2] == "status"
    return resource, namespace, name, is_status


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "tpu-operator-testenv/0.1"

    # quiet the default stderr access log
    def log_message(self, fmt: str, *args: Any) -> None:
        log.debug("apiserver: " + fmt, *args)

    @property
    def cs(self) -> FakeClientset:
        return self.server.clientset  # type: ignore[attr-defined]

    # -- helpers --------------------------------------------------------------

    def _send_json(self, code: int, obj: Any) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_error(self, e: errors.ApiError) -> None:
        self._send_json(e.code, {
            "kind": "Status", "apiVersion": "v1", "status": "Failure",
            "reason": e.reason, "message": e.message, "code": e.code,
        })

    def _read_body(self) -> Optional[dict]:
        length = int(self.headers.get("Content-Length") or 0)
        if not length:
            return None
        return json.loads(self.rfile.read(length))

    def _route(self):
        parsed = urllib.parse.urlparse(self.path)
        params = dict(urllib.parse.parse_qsl(parsed.query))
        resource, namespace, name, is_status = _parse(parsed.path)
        if resource is None:
            self._send_error(errors.ApiError(404, "NotFound",
                                             f"unknown path {parsed.path}"))
            return None
        return getattr(self.cs, resource), namespace, name, is_status, params

    # -- verbs ----------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (BaseHTTPRequestHandler API)
        routed = self._route()
        if routed is None:
            return
        client, namespace, name, _st, params = routed
        try:
            if name:
                self._send_json(200, client.get(namespace, name))
            elif params.get("watch") == "true":
                self._serve_watch(client, namespace, params)
            else:
                items, rv = client.list_with_version(
                    namespace, params.get("labelSelector", ""))
                self._send_json(200, {"kind": f"{client.kind}List",
                                      "apiVersion": "v1", "items": items,
                                      "metadata": {"resourceVersion": rv}})
        except errors.ApiError as e:
            self._send_error(e)

    def _serve_watch(self, client: Any, namespace: str, params: Dict[str, str]) -> None:
        # Raises 410 Gone (into do_GET's ApiError handler — headers not yet
        # sent) when the anchor RV predates the event-log horizon, exactly
        # the real watch-cache contract the informer's re-list path handles.
        watch = client.watch(namespace, params.get("labelSelector", ""),
                             resource_version=params.get("resourceVersion", ""))
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            for event_type, obj in watch:
                line = json.dumps({"type": event_type, "object": obj}).encode() + b"\n"
                self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away
        finally:
            watch.stop()
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass

    def _admit(self, client: Any, body: dict) -> None:
        """Schema admission for TPUJobs: validate against the structural
        openAPIV3Schema in *strict* mode (unknown fields rejected — kubectl
        --validate=strict semantics), raising 422 like a real apiserver's
        Invalid status. Other resources pass through: their schemas belong
        to upstream K8s, and the fakes stay permissive."""
        if getattr(client, "kind", "") != "TPUJob":
            return
        from tpu_operator.apis.tpujob.v1alpha1 import schema as schema_mod

        ok, message = schema_mod.validate_tpujob_strict(body)
        if not ok:
            raise errors.ApiError(422, "Invalid",
                                  f"TPUJob validation failed: {message}")

    def do_POST(self) -> None:  # noqa: N802
        routed = self._route()
        if routed is None:
            return
        client, namespace, _name, _st, _params = routed
        try:
            body = self._read_body() or {}
            self._admit(client, body)
            self._send_json(201, client.create(namespace, body))
        except errors.ApiError as e:
            self._send_error(e)

    def do_PUT(self) -> None:  # noqa: N802
        routed = self._route()
        if routed is None:
            return
        client, namespace, name, is_status, _params = routed
        body = self._read_body() or {}
        try:
            # Both branches admit: a real apiserver validates status-
            # subresource writes against the CRD's structural schema too
            # (the status enums exist to catch operator-side drift like a
            # miscased phase).
            self._admit(client, body)
            if is_status:
                self._send_json(200, client.update_status(namespace, body))
            else:
                self._send_json(200, client.update(namespace, body))
        except errors.ApiError as e:
            self._send_error(e)

    def do_DELETE(self) -> None:  # noqa: N802
        routed = self._route()
        if routed is None:
            return
        client, namespace, name, _st, params = routed
        try:
            if name:
                client.delete(namespace, name)
                self._send_json(200, {"kind": "Status", "status": "Success"})
            else:
                n = client.delete_collection(namespace, params.get("labelSelector", ""))
                self._send_json(200, {"kind": "Status", "status": "Success",
                                      "items": [None] * n})
        except errors.ApiError as e:
            self._send_error(e)


class _TrackingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that remembers accepted sockets so the harness can
    sever them abruptly (kill()) — a clean shutdown() ends chunked watch
    streams with the terminal 0-chunk, which never exercises the client's
    torn-stream (IncompleteRead) path.

    The listen backlog is raised from socketserver's default of 5: a
    parallel gang sync opens up to ``createParallelism`` connections at
    once, and an overflowed backlog drops SYNs that the clients only
    retransmit after ~1 s — turning the parallel path *slower* than
    sequential on localhost."""

    request_queue_size = 128

    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__(*args, **kwargs)
        self.client_socks: list = []

    def get_request(self):
        sock, addr = super().get_request()
        # The REST client opens one connection per request, so a long soak
        # accepts tens of thousands of sockets; keep only the live ones or
        # this list single-handedly dominates harness RSS (kill() only needs
        # sockets that still have an fd anyway).
        if len(self.client_socks) >= 512:
            self.client_socks = [
                s for s in self.client_socks if s.fileno() != -1]
        self.client_socks.append(sock)
        return sock, addr


class ApiServerHarness:
    """Lifecycle wrapper: ``with ApiServerHarness() as srv: srv.url ...``"""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 clientset: Optional[Any] = None):
        # ``clientset`` lets a caller serve a wrapped store — e.g. a
        # FlakyClientset injecting per-request latency so a localhost bench
        # has an RTT worth overlapping (handler threads sleep off-GIL).
        self.clientset = clientset if clientset is not None else FakeClientset()
        self._httpd = _TrackingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        # Never join handler threads on close: a handler can be parked inside
        # a quiet watch stream; close_watches() unblocks them, but shutdown
        # must not depend on that ordering (deadlocks teardown otherwise).
        self._httpd.block_on_close = False
        self._httpd.clientset = self.clientset  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ApiServerHarness":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name="test-apiserver",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.clientset.close_watches()  # end live streams → handlers exit
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5.0)

    def kill(self) -> None:
        """Simulate hard apiserver death: sever every accepted connection
        WITHOUT the clean chunked-stream terminator, so open watches see a
        mid-protocol EOF (http.client.IncompleteRead on the consumer side).
        This is the failure mode a real apiserver restart/LB reset produces;
        stop() can't reproduce it because close_watches() lets handlers
        finish their streams cleanly."""
        for sock in self._httpd.client_socks:
            try:
                # shutdown(), not close(): the handler's rfile/wfile makefile
                # objects hold io-refs, so close() would only drop a refcount
                # without sending FIN; shutdown() tears the TCP stream down
                # immediately regardless.
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "ApiServerHarness":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()
