"""Kwok-style fake cluster: node/kubelet state machines over the fake store.

The fleet and churn benches used to carry hand-rolled watch loops that
unconditionally succeeded every pod — no node identity, no readiness
latency, no failure (ROADMAP item 5 names the gap). This module is the
real thing, scaled the way `kwok <https://kwok.sigs.k8s.io>`_ scales it:
no containers run anywhere, but every pod the REAL operator creates is
driven through a real kubelet state machine

    Pending → bound to a Node → ContainerCreating (configurable latency)
    → Running/Ready (+ synthetic heartbeats through the real status
    server) → Succeeded / Failed

entirely via the backing :class:`~tpu_operator.client.fake.FakeClientset`
— the same store the in-process apiserver serves — so the operator binary
(REST clientset, informers, sharded workqueue, fleet scheduler) is
exercised unmodified at 10k-pod scale on one machine.

Topology: :class:`FakeNode` objects advertise the TPU resource,
``cloud.google.com/gke-tpu-topology`` and ``tpuoperator.dev/slice-id``
labels, feeding the PR-8 ``--discover-slice-inventory`` path; each node
runs a :class:`FakeKubelet` holding its pods' machines. Threading is NOT
one-per-kubelet (256 nodes must not mean 256 threads): one watch-pump
thread ingests pod events, one timer thread fires due transitions off a
heap — both consumers of the backing store, never pollers (a 20 Hz
``pods.list`` at 10k retained pods deepcopies the world under the fake
store's global lock and starves the apiserver sharing it).

On top rides :class:`StormController`: a SEEDED chaos composer whose
entire kill/flap schedule is derived from ``(seed, sorted node and slice
identities, wave config)`` and never from live pod state or wall-clock —
so one failing seed replays bit-identically (docs/design.md "Fake
cluster & storm soak"). It composes the existing chaos surfaces
(:class:`~tpu_operator.controller.chaos.FlakyClientset` error-rate
bursts, :class:`~tpu_operator.controller.chaos.ChaosMonkey` pod kills,
blob fault hooks) with the node-level injectors only this layer can
express: slice preemption storms, node NotReady/flap windows,
drain-then-return, slow-kubelet degradation.
"""

from __future__ import annotations

import heapq
import logging
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpu_operator.apis.tpujob.v1alpha1.types import (
    LABEL_ATTEMPT,
    LABEL_JOB_NAME,
    LABEL_TASK_INDEX,
)
from tpu_operator.scheduler.inventory import (
    NODE_SLICE_ID_LABEL,
    NODE_TOPOLOGY_LABEL,
)
from tpu_operator.util import lockdep

log = logging.getLogger(__name__)

DEFAULT_TPU_RESOURCE = "cloud-tpus.google.com/v4"


def ready_condition(ready: bool) -> Dict[str, str]:
    """The one node condition the discovery path reads."""
    return {"type": "Ready", "status": "True" if ready else "False"}


class KubeletProfile:
    """Timing knobs of one kubelet's state machine. All-zero is the
    *instant* profile — a pod goes straight to Succeeded in one status
    write, byte-identical to the old bench ``kubelet_sim`` closures (the
    budget benches depend on that single-update behavior)."""

    def __init__(self, create_latency: float = 0.0,
                 run_seconds: float = 0.0,
                 heartbeat_interval: float = 0.0):
        self.create_latency = max(0.0, create_latency)
        self.run_seconds = max(0.0, run_seconds)
        # 0 disables beats entirely; > 0 beats once on Running and then
        # every interval until terminal.
        self.heartbeat_interval = max(0.0, heartbeat_interval)

    @property
    def instant(self) -> bool:
        return (self.create_latency == 0.0 and self.run_seconds == 0.0
                and self.heartbeat_interval == 0.0)

    def copy(self) -> "KubeletProfile":
        return KubeletProfile(self.create_latency, self.run_seconds,
                              self.heartbeat_interval)


class FakeNode:
    """One TPU node's identity: name, slice shape, slice membership."""

    def __init__(self, name: str, resource: str = DEFAULT_TPU_RESOURCE,
                 topology: str = "2x2x2", slice_id: Optional[str] = None,
                 chips: int = 4):
        self.name = name
        self.resource = resource
        self.topology = topology
        # No slice-id label → the discovery path treats the node as its
        # own single-host slice; normalize here so storm targeting can
        # always address pods by slice.
        self.slice_id = slice_id or f"node:{name}"
        self.chips = chips

    def manifest(self, ready: bool = True) -> Dict[str, Any]:
        """The node object the discovery informer consumes."""
        return {
            "metadata": {
                "name": self.name,
                "labels": {
                    NODE_TOPOLOGY_LABEL: self.topology,
                    NODE_SLICE_ID_LABEL: self.slice_id,
                },
            },
            "status": {
                "allocatable": {self.resource: str(self.chips)},
                "conditions": [ready_condition(ready)],
            },
        }


def make_nodes(count: int, slices: int, prefix: str = "node",
               resource: str = DEFAULT_TPU_RESOURCE,
               topology: str = "2x2x2") -> List[FakeNode]:
    """``count`` nodes spread round-robin over ``slices`` slice ids."""
    return [
        FakeNode(f"{prefix}-{i:04d}", resource=resource, topology=topology,
                 slice_id=f"{prefix}-slice-{i % max(1, slices):04d}")
        for i in range(count)
    ]


class _PodSim:
    """One pod's position in the kubelet state machine. All fields are
    guarded by the owning cluster's condition (accessed only from
    ``*_locked`` paths); no lock of its own."""

    __slots__ = ("pod_name", "namespace", "node_name", "state", "container",
                 "job_name", "task_index", "attempt", "step")

    def __init__(self, pod_name: str, namespace: str, pod: Dict[str, Any]):
        self.pod_name = pod_name
        self.namespace = namespace
        self.node_name: Optional[str] = None
        self.state = "new"  # new → creating → running → done
        spec = pod.get("spec") or {}
        containers = spec.get("containers") or [{}]
        self.container = str(containers[0].get("name") or "tpu")
        labels = (pod.get("metadata") or {}).get("labels") or {}
        self.job_name = str(labels.get(LABEL_JOB_NAME, ""))
        try:
            self.task_index = int(labels.get(LABEL_TASK_INDEX, 0))
        except (TypeError, ValueError):
            self.task_index = 0
        try:
            self.attempt = int(labels.get(LABEL_ATTEMPT, 0))
        except (TypeError, ValueError):
            self.attempt = 0
        self.step = 0


class FakeKubelet:
    """One node's kubelet: holds the node identity, its timing profile
    and the names of the pods bound to it. Passive — the cluster's pump
    and timer threads drive every transition, so 256 kubelets cost zero
    threads. All mutable fields are guarded by the cluster's condition;
    every method runs with it held (the ``*_locked`` convention)."""

    def __init__(self, node: FakeNode, profile: KubeletProfile):
        self.node = node
        self.profile = profile.copy()
        self.ready = True
        self.latency_scale = 1.0  # slow-kubelet degradation multiplier
        self.pod_names: set = set()

    def create_latency_locked(self) -> float:
        return self.profile.create_latency * self.latency_scale

    def run_seconds_locked(self) -> float:
        return self.profile.run_seconds * self.latency_scale

    def bind_locked(self, sim: _PodSim) -> None:
        sim.node_name = self.node.name
        self.pod_names.add(sim.pod_name)

    def unbind_locked(self, sim: _PodSim) -> None:
        self.pod_names.discard(sim.pod_name)


class FakeCluster:
    """The assembled fake cluster over one backing FakeClientset.

    Usage::

        cluster = FakeCluster(backing, nodes=make_nodes(8, slices=8),
                              profile=KubeletProfile(0.05, 0.2, 10.0),
                              status_server=status)
        cluster.start()
        ... create TPUJobs; the real operator's pods run through the
            node/kubelet machines ...
        cluster.stop()

    With ``nodes=()`` and the default (instant) profile this is exactly
    the old bench ``kubelet_sim``: every operator-created pod succeeds in
    one status write, no binding, no latency.
    """

    # Timer tags — the per-pod transition each heap entry fires.
    _BIND, _RUN, _FINISH, _BEAT = "bind", "run", "finish", "beat"

    def __init__(self, backing: Any, namespace: str = "default",
                 nodes: Tuple[FakeNode, ...] = (),
                 profile: Optional[KubeletProfile] = None,
                 status_server: Optional[Any] = None,
                 register_nodes: bool = True):
        self._backing = backing
        self._namespace = namespace
        self._status_server = status_server
        self._profile = (profile or KubeletProfile()).copy()
        self._cond = lockdep.condition("FakeCluster._cond")
        self._pods: Dict[str, _PodSim] = {}  # guarded-by: _cond
        self._kubelets: Dict[str, FakeKubelet] = {}  # guarded-by: _cond
        # (due, seq, pod_name, tag) heap; seq breaks due-time ties so the
        # heap never compares pod names of equal-due entries unstably.
        self._timers: List[Tuple[float, int, str, str]] = []  # guarded-by: _cond
        self._seq = 0  # guarded-by: _cond
        self._stopped = False  # guarded-by: _cond
        for node in nodes:
            self._kubelets[node.name] = FakeKubelet(node, self._profile)
            if register_nodes:
                self._backing.nodes.create("", node.manifest())
        # Register the watch before any thread starts (events queue up),
        # so no pod created between start() and the first poll is lost.
        self._watch = backing.pods.watch(namespace)
        self._pump_thread = threading.Thread(
            target=self._pump, daemon=True, name="fake-cluster-pump")
        self._timer_thread = threading.Thread(
            target=self._timer_loop, daemon=True, name="fake-cluster-timer")

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FakeCluster":
        self._pump_thread.start()
        self._timer_thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._watch.stop()
        self._pump_thread.join(timeout=5.0)
        self._timer_thread.join(timeout=5.0)

    def __enter__(self) -> "FakeCluster":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- introspection -------------------------------------------------------

    def node_names(self) -> List[str]:
        with self._cond:
            return sorted(self._kubelets)

    def slice_ids(self) -> List[str]:
        with self._cond:
            return sorted({k.node.slice_id for k in self._kubelets.values()})

    def tracked_pods(self) -> int:
        with self._cond:
            return len(self._pods)

    def get_node(self, node_name: str) -> Optional[FakeNode]:
        with self._cond:
            kubelet = self._kubelets.get(node_name)
            return kubelet.node if kubelet is not None else None

    # -- node-level fault injectors (the StormController's verbs) ------------

    def set_node_ready(self, node_name: str, ready: bool) -> None:
        """Flip the node's Ready condition through the backing store —
        the node informer sees a MODIFIED event, exactly like a real
        kubelet losing/regaining its heartbeat lease."""
        with self._cond:
            kubelet = self._kubelets.get(node_name)
            if kubelet is None:
                return
            kubelet.ready = ready
            manifest = kubelet.node.manifest(ready=ready)
        try:
            node = self._backing.nodes.get("", node_name)
            node["status"] = manifest["status"]
            self._backing.nodes.update_status("", node)
        except Exception:  # noqa: BLE001 — raced a drain
            pass

    def drain_node(self, node_name: str) -> List[str]:
        """Delete the node object (DELETED watch event → inventory
        shrink) and preempt every pod bound to it; returns the preempted
        pod names."""
        victims = self.preempt_nodes([node_name])
        with self._cond:
            self._kubelets.pop(node_name, None)
        try:
            self._backing.nodes.delete("", node_name)
        except Exception:  # noqa: BLE001 — already drained
            pass
        return victims

    def return_node(self, node: FakeNode) -> None:
        """Bring a drained node back (ADDED watch event → inventory grow)."""
        with self._cond:
            self._kubelets[node.name] = FakeKubelet(node, self._profile)
        try:
            self._backing.nodes.create("", node.manifest())
        except Exception:  # noqa: BLE001 — never drained
            pass

    def preempt_slices(self, slice_ids: List[str]) -> List[str]:
        """Slice preemption storm: every non-terminal pod bound to a node
        of these slices dies at once with the kubelet-level ``Preempted``
        reason and no container record — the exact shape
        trainer/policy.py classifies as a PREEMPTION-kind (not
        application-kind) restart."""
        with self._cond:
            wanted = set(slice_ids)
            names = [k.node.name for k in self._kubelets.values()
                     if k.node.slice_id in wanted]
        return self.preempt_nodes(names)

    def preempt_nodes(self, node_names: List[str]) -> List[str]:
        with self._cond:
            wanted = set(node_names)
            victims = [sim for sim in self._pods.values()
                       if sim.node_name in wanted and sim.state != "done"]
            for sim in victims:
                self._mark_done_locked(sim)
        return self._preempt(victims)

    def preempt_pods(self, pod_names: List[str]) -> List[str]:
        """Preempt specific pods by name (tests target one generation
        deterministically; slice/node storms resolve to this shape)."""
        with self._cond:
            wanted = set(pod_names)
            victims = [sim for sim in self._pods.values()
                       if sim.pod_name in wanted and sim.state != "done"]
            for sim in victims:
                self._mark_done_locked(sim)
        return self._preempt(victims)

    def _preempt(self, victims: List[_PodSim]) -> List[str]:
        for sim in victims:
            self._apply_status(sim, {"phase": "Failed",
                                     "reason": "Preempted"})
        return [sim.pod_name for sim in victims]

    def scale_kubelet_latency(self, scale: float) -> None:
        """Slow-kubelet degradation window: multiply every pending and
        future create/run latency (1.0 restores)."""
        with self._cond:
            for kubelet in self._kubelets.values():
                kubelet.latency_scale = max(0.0, scale)

    # -- pod state machine ---------------------------------------------------

    def _pump(self) -> None:
        """Watch-pump thread: ingest pod events into sims + timers. No
        status writes happen here — the timer thread owns every
        transition, so one pod's updates are totally ordered."""
        for event_type, pod in self._watch:
            md = pod.get("metadata") or {}
            pod_name = str(md.get("name") or "")
            if not pod_name:
                continue
            if event_type == "DELETED":
                with self._cond:
                    sim = self._pods.pop(pod_name, None)
                    if sim is not None and sim.node_name:
                        kubelet = self._kubelets.get(sim.node_name)
                        if kubelet is not None:
                            kubelet.unbind_locked(sim)
                continue
            if event_type not in ("ADDED", "MODIFIED"):
                continue
            if (pod.get("status") or {}).get("phase"):
                continue  # our own echo, or a foreign pre-statused pod
            with self._cond:
                if self._stopped or pod_name in self._pods:
                    continue
                sim = _PodSim(pod_name, str(md.get("namespace")
                                            or self._namespace), pod)
                self._pods[pod_name] = sim
                self._schedule_locked(0.0, pod_name, self._BIND)
                self._cond.notify_all()

    def _schedule_locked(self, delay: float, pod_name: str, tag: str) -> None:
        self._seq += 1
        heapq.heappush(self._timers,
                       (time.monotonic() + delay, self._seq, pod_name, tag))

    def _timer_loop(self) -> None:
        """Timer thread: pop due transitions under the condition, fire
        them outside it (every fire writes the backing store / status
        server — never under the lock)."""
        while True:
            due: List[Tuple[str, str]] = []
            with self._cond:
                if self._stopped:
                    return
                now = time.monotonic()
                while self._timers and self._timers[0][0] <= now:
                    _due, _seq, pod_name, tag = heapq.heappop(self._timers)
                    due.append((pod_name, tag))
                if not due:
                    timeout = (self._timers[0][0] - now
                               if self._timers else 0.5)
                    self._cond.wait(timeout=min(0.5, max(0.001, timeout)))
                    continue
            for pod_name, tag in due:
                self._fire(pod_name, tag)

    def _fire(self, pod_name: str, tag: str) -> None:
        status: Optional[Dict[str, Any]] = None
        beat: Optional[Dict[str, Any]] = None
        with self._cond:
            if self._stopped:
                return
            sim = self._pods.get(pod_name)
            if sim is None or sim.state == "done":
                return  # deleted or preempted since scheduling
            if tag == self._BIND:
                status = self._bind_locked(sim)
            elif tag == self._RUN:
                sim.state = "running"
                status = {
                    "phase": "Running",
                    "conditions": [{"type": "Ready", "status": "True"}],
                    "containerStatuses": [{
                        "name": sim.container, "ready": True,
                        "state": {"running": {}}}],
                }
                self._schedule_locked(self._run_seconds_locked(sim),
                                      pod_name, self._FINISH)
                if self._beat_enabled_locked():
                    beat = self._beat_body_locked(sim)
                    self._schedule_locked(self._profile.heartbeat_interval,
                                          pod_name, self._BEAT)
            elif tag == self._BEAT:
                if sim.state == "running" and self._beat_enabled_locked():
                    beat = self._beat_body_locked(sim)
                    self._schedule_locked(self._profile.heartbeat_interval,
                                          pod_name, self._BEAT)
            elif tag == self._FINISH:
                self._mark_done_locked(sim)
                status = {
                    "phase": "Succeeded",
                    "containerStatuses": [{
                        "name": sim.container,
                        "state": {"terminated": {"exitCode": 0}}}],
                }
        if status is not None:
            self._apply_status(sim, status)
        if beat is not None and self._status_server is not None:
            try:
                # Rejections are legitimate (the job may already be
                # deleted); the real payload tolerates them the same way.
                self._status_server.record_heartbeat(beat)
            except Exception:  # noqa: BLE001 — raced a server stop
                pass

    def _bind_locked(self, sim: _PodSim) -> Optional[Dict[str, Any]]:
        """Bind to a ready node (or no node when the cluster models
        none) and enter ContainerCreating; instant profile jumps straight
        to the terminal single-write the budget benches expect."""
        if self._kubelets:
            ready = [self._kubelets[n] for n in sorted(self._kubelets)
                     if self._kubelets[n].ready]
            if not ready:
                # No schedulable node right now: stay Pending, retry —
                # exactly a scheduler waiting out a NotReady window.
                self._schedule_locked(0.2, sim.pod_name, self._BIND)
                return None
            kubelet = ready[self._seq % len(ready)]
            kubelet.bind_locked(sim)
            create_latency = kubelet.create_latency_locked()
        else:
            create_latency = self._profile.create_latency
        if self._profile.instant:
            self._mark_done_locked(sim)
            return {
                "phase": "Succeeded",
                "containerStatuses": [{
                    "name": sim.container,
                    "state": {"terminated": {"exitCode": 0}}}],
            }
        sim.state = "creating"
        self._schedule_locked(create_latency, sim.pod_name, self._RUN)
        return {
            "phase": "Pending",
            "conditions": [{"type": "PodScheduled", "status": "True"}],
            "containerStatuses": [{
                "name": sim.container, "ready": False,
                "state": {"waiting": {"reason": "ContainerCreating"}}}],
        }

    def _run_seconds_locked(self, sim: _PodSim) -> float:
        kubelet = self._kubelets.get(sim.node_name or "")
        if kubelet is not None:
            return kubelet.run_seconds_locked()
        return self._profile.run_seconds

    def _beat_enabled_locked(self) -> bool:
        return (self._profile.heartbeat_interval > 0
                and self._status_server is not None)

    def _beat_body_locked(self, sim: _PodSim) -> Dict[str, Any]:
        sim.step += 1
        return {
            "namespace": sim.namespace, "name": sim.job_name,
            "processId": sim.task_index, "attempt": sim.attempt,
            "step": sim.step, "stepTimeSeconds": 0.1, "loss": 1.0,
            "lastCheckpointStep": max(0, sim.step - 1),
        }

    def _mark_done_locked(self, sim: _PodSim) -> None:
        sim.state = "done"
        if sim.node_name:
            kubelet = self._kubelets.get(sim.node_name)
            if kubelet is not None:
                kubelet.unbind_locked(sim)

    def _apply_status(self, sim: _PodSim, status: Dict[str, Any]) -> None:
        """One pod status write through the backing store, kubelet-style:
        read-modify-write so spec.nodeName binding and status land
        together. Retries a 409 (another writer slipped between read and
        write); losing the pod to a teardown is normal and final."""
        for _ in range(3):
            try:
                pod = self._backing.pods.get(sim.namespace, sim.pod_name)
                if sim.node_name:
                    pod.setdefault("spec", {})["nodeName"] = sim.node_name
                pod["status"] = status
                self._backing.pods.update(sim.namespace, pod)
                return
            except Exception as e:  # noqa: BLE001 — raced a teardown
                if getattr(e, "code", None) == 409:
                    continue
                return


# --- seeded storms ------------------------------------------------------------

class StormEvent:
    """One scheduled injection. ``at`` is seconds from storm start."""

    __slots__ = ("at", "kind", "params")

    def __init__(self, at: float, kind: str, params: Dict[str, Any]):
        self.at = at
        self.kind = kind
        self.params = params

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={self.params[k]!r}"
                          for k in sorted(self.params))
        return f"StormEvent(at={self.at:.3f}, kind={self.kind!r}, {inner})"


class StormController:
    """Seeded chaos composer over a :class:`FakeCluster`.

    The ENTIRE schedule — which slices a preemption wave hits, which
    nodes flap, when each fault window opens and closes — is computed in
    :meth:`plan` from ``seed`` + the cluster's *sorted* node/slice
    identities + the wave list, and never from live pod state or
    wall-clock. Same seed, same cluster shape → bit-identical schedule
    (asserted by tests/test_fake_cluster.py), which is what makes a
    failing soak seed reproducible from its printed number alone.

    Wave kinds (the storm catalog; docs/design.md):

    - ``preempt``  — kill every pod on ``count`` seeded-chosen slices,
      swept ``sweeps`` times ``interval`` apart (a preemption window)
    - ``flap``     — ``count`` nodes NotReady for ``down_seconds``, then
      Ready again (inside the inventory debounce window = absorbed)
    - ``drain``    — delete a node, return it after ``down_seconds``
    - ``api_fault``— raise the FlakyClientset's error rate to ``rate``
      for ``seconds``
    - ``slow_kubelet`` — multiply kubelet latencies by ``scale`` for
      ``seconds``
    - ``pod_kill`` — one ChaosMonkey ``kill_once`` sweep
    - ``blob_fault`` — call ``blob_arm()`` / ``blob_disarm()`` around a
      ``seconds`` window (the store-layer fault hook surface)
    - ``coop_drain`` — call ``drain_request()``: the harness's hook into
      the cooperative-drain protocol (stamp a ``status.drain`` directive
      the way the controller's resize/preemption/maintenance call sites
      do). The storm only *requests*; whether the payload ACKs or the
      deadline hard-kills is the scenario under test.
    """

    def __init__(self, cluster: FakeCluster, seed: int,
                 waves: Tuple[Tuple[float, str, Dict[str, Any]], ...],
                 flaky: Optional[Any] = None,
                 monkey: Optional[Any] = None,
                 blob_arm: Optional[Callable[[], None]] = None,
                 blob_disarm: Optional[Callable[[], None]] = None,
                 drain_request: Optional[Callable[[], None]] = None):
        self.cluster = cluster
        self.seed = seed
        self.waves = tuple(waves)
        self.flaky = flaky
        self.monkey = monkey
        self.blob_arm = blob_arm
        self.blob_disarm = blob_disarm
        self.drain_request = drain_request
        # Identity snapshot at construction: the plan must not drift if
        # a drain wave later removes a node.
        self._node_names = tuple(cluster.node_names())
        self._slice_ids = tuple(cluster.slice_ids())
        self._drained: Dict[str, FakeNode] = {}
        self.window: Optional[Tuple[float, float]] = None
        # Realized disruption tally (pods preempted/killed/drained) —
        # what the soak gate checks to prove the storm actually landed
        # (scheduler counters only see *eviction* preemptions, not these
        # kubelet-level deaths).
        self.stats: Dict[str, int] = {"preempted_pods": 0,
                                      "killed_pods": 0,
                                      "drained_pods": 0}

    def plan(self) -> List[StormEvent]:
        """The full deterministic schedule, paired end events included."""
        rng = random.Random(self.seed)
        events: List[StormEvent] = []
        for at, kind, params in self.waves:
            if kind == "preempt":
                count = min(int(params.get("count", 1)),
                            len(self._slice_ids))
                targets = sorted(rng.sample(self._slice_ids, count)) \
                    if count else []
                # A real preemption takes the slice down for a WINDOW,
                # not an instant: sweep the same seeded targets several
                # times so pods created mid-wave die too (and so a storm
                # can't whiff on a fleet of short-lived pods).
                sweeps = max(1, int(params.get("sweeps", 1)))
                interval = float(params.get("interval", 0.5))
                for s in range(sweeps):
                    events.append(StormEvent(at + s * interval, "preempt",
                                             {"slice_ids": targets}))
            elif kind == "flap":
                count = min(int(params.get("count", 1)),
                            len(self._node_names))
                down = float(params.get("down_seconds", 0.5))
                targets = sorted(rng.sample(self._node_names, count)) \
                    if count else []
                events.append(StormEvent(at, "flap_down",
                                         {"nodes": targets}))
                events.append(StormEvent(at + down, "flap_up",
                                         {"nodes": targets}))
            elif kind == "drain":
                if not self._node_names:
                    continue
                target = rng.choice(sorted(self._node_names))
                down = float(params.get("down_seconds", 1.0))
                events.append(StormEvent(at, "drain", {"node": target}))
                events.append(StormEvent(at + down, "return",
                                         {"node": target}))
            elif kind == "api_fault":
                rate = float(params.get("rate", 0.1))
                seconds = float(params.get("seconds", 2.0))
                events.append(StormEvent(at, "api_fault_on",
                                         {"rate": rate}))
                events.append(StormEvent(at + seconds, "api_fault_off", {}))
            elif kind == "slow_kubelet":
                scale = float(params.get("scale", 4.0))
                seconds = float(params.get("seconds", 2.0))
                events.append(StormEvent(at, "slow_on", {"scale": scale}))
                events.append(StormEvent(at + seconds, "slow_off", {}))
            elif kind == "pod_kill":
                events.append(StormEvent(at, "pod_kill", {}))
            elif kind == "blob_fault":
                seconds = float(params.get("seconds", 2.0))
                events.append(StormEvent(at, "blob_on", {}))
                events.append(StormEvent(at + seconds, "blob_off", {}))
            elif kind == "coop_drain":
                events.append(StormEvent(at, "coop_drain", {}))
            else:
                raise ValueError(f"unknown storm kind {kind!r}")
        events.sort(key=lambda e: (e.at, e.kind))
        return events

    def run(self, stop_event: Optional[threading.Event] = None) -> None:
        """Play the plan against the live cluster. Blocking — benches run
        it in a thread. Records the realized (start, end) monotonic
        window in ``self.window`` for during-storm assertions."""
        events = self.plan()
        t0 = time.monotonic()
        for event in events:
            delay = t0 + event.at - time.monotonic()
            if delay > 0:
                if stop_event is not None:
                    if stop_event.wait(delay):
                        break
                else:
                    time.sleep(delay)
            self._apply(event)
        self.window = (t0, time.monotonic())

    def _apply(self, event: StormEvent) -> None:
        log.info("storm: %r", event)
        kind, p = event.kind, event.params
        if kind == "preempt":
            self.stats["preempted_pods"] += len(
                self.cluster.preempt_slices(p["slice_ids"]))
        elif kind == "flap_down":
            for node in p["nodes"]:
                self.cluster.set_node_ready(node, False)
        elif kind == "flap_up":
            for node in p["nodes"]:
                self.cluster.set_node_ready(node, True)
        elif kind == "drain":
            node = self.cluster.get_node(p["node"])
            if node is not None:
                self._drained[p["node"]] = node
                self.stats["drained_pods"] += len(
                    self.cluster.drain_node(p["node"]))
        elif kind == "return":
            node = self._drained.pop(p["node"], None)
            if node is not None:
                self.cluster.return_node(node)
        elif kind == "api_fault_on" and self.flaky is not None:
            self.flaky.error_rate = p["rate"]
        elif kind == "api_fault_off" and self.flaky is not None:
            self.flaky.error_rate = 0.0
        elif kind == "slow_on":
            self.cluster.scale_kubelet_latency(p["scale"])
        elif kind == "slow_off":
            self.cluster.scale_kubelet_latency(1.0)
        elif kind == "pod_kill" and self.monkey is not None:
            self.stats["killed_pods"] += self.monkey.kill_once()
        elif kind == "blob_on" and self.blob_arm is not None:
            self.blob_arm()
        elif kind == "blob_off" and self.blob_disarm is not None:
            self.blob_disarm()
        elif kind == "coop_drain" and self.drain_request is not None:
            self.drain_request()
            self.stats["coop_drains"] = self.stats.get("coop_drains", 0) + 1
