"""The shared polling helper for telemetry/e2e test harnesses.

Every e2e harness needs "poll until the operator converges"; before this
module each test file carried its own ad-hoc ``wait_for`` whose timeout
produced a bare ``assert False`` — the flake report said *that* it timed
out, never *what* the poller last saw (the PR 7 reflector bug cost a day
of re-running exactly because of this). One definition, two upgrades:

- **Timeout raises** :class:`WaitTimeout` (an ``AssertionError`` subclass,
  so ``pytest.raises``/``assert``-style handling both work) carrying the
  deadline AND the last observed value — a failed wait reports the state
  it saw, not just that it waited.
- **``describe``** lets call sites attach a state probe richer than the
  predicate's falsy return (e.g. the full job status while waiting on one
  phase field), evaluated only on failure so the happy path stays cheap.

Use :func:`make_wait_for` to bind per-harness defaults::

    from tpu_operator.testing.waiting import make_wait_for
    wait_for = make_wait_for(timeout=60.0, interval=0.25)
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Optional

DEFAULT_TIMEOUT = 20.0
DEFAULT_INTERVAL = 0.05


class WaitTimeout(AssertionError):
    """A wait_for deadline expired; the message carries the last state."""


def wait_for(pred: Callable[[], Any], timeout: float = DEFAULT_TIMEOUT,
             interval: float = DEFAULT_INTERVAL,
             message: str = "condition",
             describe: Optional[Callable[[], Any]] = None,
             clock: Callable[[], float] = time.monotonic,
             sleep: Callable[[float], None] = time.sleep) -> Any:
    """Poll ``pred`` until truthy; return its value. On deadline, raise
    :class:`WaitTimeout` naming the timeout and the last observed state
    (``describe()`` when given, else the predicate's last return) — so a
    flake reports what it saw instead of a bare timeout.

    A predicate that RAISES propagates immediately (a broken probe is a
    test bug, not a condition to wait out)."""
    deadline = clock() + timeout
    last: Any = None
    while True:
        last = pred()
        if last:
            return last
        if clock() >= deadline:
            observed: Any = last
            if describe is not None:
                try:
                    observed = describe()
                except Exception as e:  # noqa: BLE001 — best-effort probe
                    observed = f"<describe() failed: {e}>"
            raise WaitTimeout(
                f"{message} not met within {timeout:.1f}s; "
                f"last observed: {observed!r}")
        sleep(interval)


def make_wait_for(timeout: float = DEFAULT_TIMEOUT,
                  interval: float = DEFAULT_INTERVAL
                  ) -> Callable[..., Any]:
    """Bind harness-level defaults (call sites can still override
    per-call)."""
    return functools.partial(wait_for, timeout=timeout, interval=interval)
