"""Deterministic interleaving harness: permuted schedules over named steps
and yield points.

The operator's concurrency bugs live in *interleavings* — admission vs.
teardown-release vs. restart rebuild, write-behind enqueue vs.
close()-drain — that a soak only hits by luck. This module makes the
schedule the test input (the CHESS idea, sized for this repo):

- :func:`merge_orders` enumerates every interleaving of per-thread step
  sequences, and :func:`run_order` executes one — single-threaded,
  which is exact for steps that are atomic under the subsystem's lock
  (every public FleetScheduler/Controller entry point is). A triple
  with 2–3 steps per logical thread is a few dozen schedules: cheap
  enough to run exhaustively in a unit test.
- :class:`InterleavingScheduler` runs steps on REAL threads, one
  runnable at a time, choosing the next thread at every boundary with a
  seeded RNG — for scenarios where thread identity matters (reentrant
  locks, thread-local state) or where production threads participate
  via yield points (:mod:`tpu_operator.util.yieldpoints`): a production
  thread hitting ``pause(...)`` is adopted into the schedule. A step
  that blocks on real synchronization is detected by timeout and the
  token moves on; it rejoins at its next yield point — schedules stay
  reproducible whenever steps don't block, and merely lose strictness
  (never correctness) when they do.
- :class:`PointGate` is the scalpel: hold any named yield point, let
  the test thread interleave operations into the exposed window, then
  release — the way to pin a race whose window is *inside* one method.

Yield points are cheap no-ops in production (util/yieldpoints.py); only
harness-installed hooks give them meaning.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, \
    Tuple

from tpu_operator.util import yieldpoints

Step = Callable[[], Any]


# --- exhaustive, single-threaded schedules -----------------------------------

def merge_orders(*lengths: int) -> Iterator[Tuple[int, ...]]:
    """Every interleaving of sequences with the given lengths, as tuples
    of sequence indexes (``(0, 1, 0)`` = seq0 step, seq1 step, seq0
    step). The count is the multinomial coefficient — callers keep
    per-thread step counts small on purpose."""
    labels: List[int] = []
    for idx, n in enumerate(lengths):
        labels.extend([idx] * n)
    seen = set()
    for perm in itertools.permutations(labels):
        if perm not in seen:
            seen.add(perm)
            yield perm


def run_order(threads: Sequence[Sequence[Step]],
              order: Sequence[int]) -> List[Any]:
    """Execute one merge order over per-thread step lists; returns each
    step's return value in execution order."""
    cursors = [0] * len(threads)
    results: List[Any] = []
    for tid in order:
        step = threads[tid][cursors[tid]]
        cursors[tid] += 1
        results.append(step())
    for tid, cur in enumerate(cursors):
        if cur != len(threads[tid]):
            raise ValueError(f"order {order!r} leaves thread {tid} with "
                             f"{len(threads[tid]) - cur} unexecuted steps")
    return results


def exhaustive(scenario: Callable[[], Sequence[Sequence[Step]]],
               check: Optional[Callable[[Sequence[int]], None]] = None
               ) -> int:
    """Run ``scenario()`` (which builds FRESH state and returns the
    per-thread step lists) under every merge order; ``check(order)``
    runs after each schedule against the state the steps closed over.
    Returns the number of schedules executed."""
    first = scenario()
    lengths = [len(t) for t in first]
    count = 0
    for order in merge_orders(*lengths):
        # The probe build runs the first schedule; later schedules each
        # get a fresh one (scenario() can be an expensive setup).
        threads = first if first is not None else scenario()
        first = None
        run_order(threads, order)
        if check is not None:
            check(order)
        count += 1
    return count


# --- seeded cooperative scheduler over real threads --------------------------

class _Task:
    __slots__ = ("name", "steps", "thread", "go", "parked", "done",
                 "adopted", "error")

    def __init__(self, name: str, steps: Sequence[Step],
                 adopted: bool = False):
        self.name = name
        self.steps = list(steps)
        self.thread: Optional[threading.Thread] = None
        self.go = threading.Event()       # token grant
        self.parked = threading.Event()   # task is waiting at a boundary
        self.done = False
        self.adopted = adopted
        self.error: Optional[BaseException] = None


class InterleavingScheduler:
    """One-runnable-at-a-time token scheduler with seeded choices.

    ``add(name, *steps)`` registers a logical thread; ``run()`` executes
    all of them, passing the token per the seeded RNG at every step
    boundary and every ``yieldpoints.pause`` a running thread hits.
    Production threads (started by the code under test) that reach a
    yield point while the scheduler is installed are ADOPTED: they park
    like any task and get scheduled by name. The decision trace is
    recorded in ``trace`` so a failing seed prints its schedule."""

    def __init__(self, seed: int = 0, step_timeout: float = 5.0):
        self._rng = random.Random(seed)
        self.seed = seed
        self._timeout = step_timeout
        self._lock = threading.Lock()
        self._tasks: Dict[str, _Task] = {}
        self.trace: List[str] = []
        self._running = False

    def add(self, name: str, *steps: Step) -> None:
        if name in self._tasks:
            raise ValueError(f"duplicate task {name!r}")
        self._tasks[name] = _Task(name, steps)

    # -- yield-point integration ----------------------------------------------

    def _on_pause(self, point: str) -> None:
        me = threading.current_thread()
        with self._lock:
            if not self._running:
                return
            task = next((t for t in self._tasks.values()
                         if t.thread is me), None)
            if task is None:
                # A production thread surfaced at a yield point: adopt it
                # under the point's name so the seeded choice includes it.
                # Uniquified — a SECOND thread at the same point must not
                # overwrite the first's task (which would orphan that
                # thread at go.wait() with nothing left to wake it).
                name = f"@{point}"
                n = 2
                while name in self._tasks:
                    name = f"@{point}#{n}"
                    n += 1
                task = _Task(name, [], adopted=True)
                task.thread = me
                self._tasks[task.name] = task
        if task is None:
            return
        self.trace.append(f"{task.name} paused at {point}")
        self._park(task)

    def _park(self, task: "_Task") -> None:
        """Park until granted — with a teardown handshake: if run()'s
        finally already declared the schedule over, the blanket wakeup it
        issued may have raced our go.clear(), so re-check under the lock
        and self-wake rather than waiting forever on a dead scheduler."""
        task.go.clear()
        task.parked.set()
        with self._lock:
            if not self._running:
                task.go.set()
        task.go.wait()

    # -- task bodies ----------------------------------------------------------

    def _body(self, task: _Task) -> None:
        task.go.wait()
        try:
            for step in task.steps:
                step()
                # Boundary between steps: park and hand the token back.
                self._park(task)
        except BaseException as e:  # noqa: BLE001 — reported by run()
            task.error = e
        finally:
            task.done = True
            task.parked.set()

    # -- the schedule loop -----------------------------------------------------

    def run(self) -> None:
        """Execute every registered task to completion. Raises the first
        task error (with the schedule trace attached) and RuntimeError on
        a harness-level deadlock (no task can make progress)."""
        yieldpoints.install(self._on_pause)
        self._running = True
        try:
            for task in self._tasks.values():
                t = threading.Thread(target=self._body, args=(task,),
                                     daemon=True,
                                     name=f"sched-{task.name}")
                task.thread = t
                task.parked.set()  # ready for its first grant
                t.start()
            stalled: set = set()
            while True:
                with self._lock:
                    candidates = sorted(
                        name for name, t in self._tasks.items()
                        if not t.done and t.parked.is_set())
                    # Adopted production threads (daemon loops) never
                    # "finish" — only spawned tasks gate termination.
                    live = [name for name, t in self._tasks.items()
                            if not t.done and not t.adopted]
                if not live:
                    break
                if not candidates:
                    # Nobody parked: every live task is running free or
                    # blocked on real sync. Wait for one to park/finish.
                    if not self._wait_any_parked(live):
                        raise RuntimeError(
                            f"schedule deadlock (seed {self.seed}): live "
                            f"tasks {live} never reached a boundary; "
                            f"trace: {self.trace}")
                    continue
                name = (candidates[0] if len(candidates) == 1
                        else self._rng.choice(candidates))
                task = self._tasks[name]
                self.trace.append(f"grant {name}")
                task.parked.clear()
                task.go.set()
                if not task.parked.wait(self._timeout):
                    # The step blocked on real synchronization: release
                    # the token elsewhere; the task rejoins when whatever
                    # it waits on is released by a later-scheduled task.
                    stalled.add(name)
                    self.trace.append(f"{name} stalled (blocked in step)")
            errors = [t for t in self._tasks.values() if t.error is not None]
            if errors:
                first = errors[0]
                raise AssertionError(
                    f"task {first.name!r} failed under seed {self.seed} "
                    f"(schedule: {self.trace})") from first.error
        finally:
            # Teardown handshake with _park: flip _running and snapshot
            # the task set under the lock, so a thread adopted
            # concurrently is either in the snapshot (woken below) or
            # observes _running=False in _park and self-wakes — and the
            # iteration can't race an adoption insert.
            with self._lock:
                self._running = False
                tasks = list(self._tasks.values())
            yieldpoints.uninstall()
            for task in tasks:
                task.go.set()

    def _wait_any_parked(self, names: List[str],
                         timeout: Optional[float] = None) -> bool:
        deadline = (timeout if timeout is not None else self._timeout)
        interval = 0.002
        waited = 0.0
        while waited < deadline:
            for name in names:
                t = self._tasks.get(name)
                if t is not None and (t.done or t.parked.is_set()):
                    return True
            time.sleep(interval)
            waited += interval
        return False


def run_seeds(build: Callable[[InterleavingScheduler], None],
              seeds: Sequence[int] = range(16),
              step_timeout: float = 5.0) -> int:
    """Run a scenario under many seeds: ``build(sched)`` registers tasks
    against FRESH state per seed (closures own the state and assert in
    their final steps). Returns the number of schedules run."""
    for seed in seeds:
        sched = InterleavingScheduler(seed=seed, step_timeout=step_timeout)
        build(sched)
        sched.run()
    return len(list(seeds))


# --- surgical yield-point gating ---------------------------------------------

class PointGate:
    """Hold real threads at named yield points; release them on cue.

    The tool for races whose window is INSIDE one method: hold the
    point that exposes the window, drive the racing operation from the
    test thread, release, and assert. Use as a context manager (installs
    and uninstalls the global yield-point hook)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._held: set = set()          # guarded-by: _cond
        self._blocked: Dict[str, int] = {}  # point -> waiter count; guarded-by: _cond
        self._passed: Dict[str, int] = {}  # point -> pass-throughs; guarded-by: _cond

    def __enter__(self) -> "PointGate":
        yieldpoints.install(self._on_pause)
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release_all()
        yieldpoints.uninstall()

    def _on_pause(self, name: str) -> None:
        with self._cond:
            self._passed[name] = self._passed.get(name, 0) + 1
            if name not in self._held:
                self._cond.notify_all()
                return
            self._blocked[name] = self._blocked.get(name, 0) + 1
            self._cond.notify_all()
            while name in self._held:
                self._cond.wait()
            self._blocked[name] -= 1
            self._cond.notify_all()

    def hold(self, name: str) -> None:
        """Arm the gate: the next thread reaching ``name`` parks."""
        with self._cond:
            self._held.add(name)

    def wait_blocked(self, name: str, timeout: float = 5.0) -> bool:
        """Wait until a thread is parked at ``name``."""
        with self._cond:
            return self._cond.wait_for(
                lambda: self._blocked.get(name, 0) > 0, timeout)

    def wait_passed(self, name: str, count: int = 1,
                    timeout: float = 5.0) -> bool:
        """Wait until ``name`` has been reached ``count`` times in total
        (parked or passed through)."""
        with self._cond:
            return self._cond.wait_for(
                lambda: self._passed.get(name, 0) >= count, timeout)

    def release(self, name: str) -> None:
        with self._cond:
            self._held.discard(name)
            self._cond.notify_all()

    def release_all(self) -> None:
        with self._cond:
            self._held.clear()
            self._cond.notify_all()
