"""Function-level call tracing.

Reference parity: the go-tracey subsystem (SURVEY.md §5) — the reference
wraps nearly every function in ``defer Exit(Enter("file $FN"))``
(e.g. server.go:46,55; controller.go:47,92; training.go:41,75;
replicas.go:82), printing nested ENTER/EXIT lines to stdout, plus a logrus
hook tagging each log line with its source file (main.go:27-32).

Re-designed rather than translated: one ``@traced`` decorator per function
(applied where the reference had the defer pairs), a thread-local depth
counter for nesting, and an off-by-default switch — the reference traced
unconditionally, which is noisy; here ``enable()`` is wired to the
``--trace`` flag. Also provides ``install_filename_log_format`` for the
source-file log tag.
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from typing import Any, Callable, TypeVar

F = TypeVar("F", bound=Callable[..., Any])

_local = threading.local()
_enabled = False
_logger = logging.getLogger("tpu_operator.trace")


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def is_enabled() -> bool:
    return _enabled


def _depth() -> int:
    return getattr(_local, "depth", 0)


def traced(fn: F) -> F:
    """Trace entry/exit of fn with nesting and wall time
    (ref: tracey.New Enter/Exit defers)."""

    label = f"{fn.__module__.rsplit('.', 1)[-1]}.{fn.__qualname__}"

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        if not _enabled:
            return fn(*args, **kwargs)
        depth = _depth()
        pad = "  " * depth
        _logger.info("%s[%d]ENTER: %s", pad, depth, label)
        _local.depth = depth + 1
        start = time.monotonic()
        try:
            return fn(*args, **kwargs)
        finally:
            _local.depth = depth
            _logger.info(
                "%s[%d]EXIT:  %s (%.1fms)", pad, depth, label,
                (time.monotonic() - start) * 1e3,
            )

    return wrapper  # type: ignore[return-value]


class _FilenameFilter(logging.Filter):
    """Attach short source-file tag (ref: logrus filename hook, main.go:27-32)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.srcfile = f"{record.filename}:{record.lineno}"
        return True


def install_filename_log_format(json_format: bool = False, level: int = logging.INFO) -> None:
    """Configure root logging with source-file tags; JSON format optional
    (ref: --json-log-format for Stackdriver, main.go:40-43)."""
    root = logging.getLogger()
    root.setLevel(level)
    handler = logging.StreamHandler()
    handler.addFilter(_FilenameFilter())
    if json_format:
        import json as _json

        class _JsonFormatter(logging.Formatter):
            def format(self, record: logging.LogRecord) -> str:
                return _json.dumps(
                    {
                        "severity": record.levelname,
                        "message": record.getMessage(),
                        "file": getattr(record, "srcfile", ""),
                        "logger": record.name,
                        "timestamp": self.formatTime(record),
                    }
                )

        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(srcfile)s %(message)s")
        )
    root.handlers[:] = [handler]
