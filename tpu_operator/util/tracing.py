"""Structured call tracing: spans, trace IDs, and the --trace log stream.

Reference parity: the go-tracey subsystem (SURVEY.md §5) — the reference
wraps nearly every function in ``defer Exit(Enter("file $FN"))``
(e.g. server.go:46,55; controller.go:47,92; training.go:41,75;
replicas.go:82), printing nested ENTER/EXIT lines to stdout, plus a logrus
hook tagging each log line with its source file (main.go:27-32).

Re-designed rather than translated, in two layers:

- **Spans** (always on, cheap): every ``@traced`` function and every
  explicit ``with span("name", key=...)`` block records a structured span —
  trace id, span id, parent id, wall-clock start, duration — into a
  bounded in-memory ring buffer. The controller opens one *root* span per
  reconcile, so every downstream ``@traced`` call nests under a single
  trace id, and every log record emitted inside the trace carries that id
  (``trace=<id>`` via the logging filter below). The status server exposes
  the buffer at ``GET /api/traces``.
- **ENTER/EXIT log lines** (off by default): the reference traced
  unconditionally, which is noisy; here ``enable()`` is wired to the
  ``--trace`` flag and reuses the same span machinery for the nested
  ENTER/EXIT stream.

Also provides ``install_filename_log_format`` for the source-file log tag.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import logging
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, TypeVar

from tpu_operator.util import lockdep

F = TypeVar("F", bound=Callable[..., Any])

_local = threading.local()
# Single-writer bool flipped by CLI wiring before threads start; reads
# are racy-but-benign (a span logged one tick late), so it carries no
# lock by design.
_enabled = False
_logger = logging.getLogger("tpu_operator.trace")

DEFAULT_SPAN_BUFFER = 512

_spans_lock = lockdep.lock("tracing._spans_lock")
# Every thread's completed spans funnel here (reconcile workers, HTTP
# handlers, informer threads) — the one cross-thread structure in this
# module; _local holds everything per-thread.
_spans: "collections.deque" = collections.deque(
    maxlen=DEFAULT_SPAN_BUFFER)  # guarded-by: _spans_lock


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def is_enabled() -> bool:
    return _enabled


def configure(span_buffer: int = DEFAULT_SPAN_BUFFER) -> None:
    """Resize the span ring buffer (wired to --trace-buffer); 0 disables
    buffering entirely (spans still carry trace ids into log records)."""
    global _spans
    with _spans_lock:
        _spans = collections.deque(_spans, maxlen=max(0, span_buffer))


def _new_id(nbytes: int) -> str:
    # Per-thread PRNG seeded once from the OS: spans are always on, so ids
    # must not cost a syscall per @traced call on the reconcile path.
    rng = getattr(_local, "rng", None)
    if rng is None:
        rng = random.Random(int.from_bytes(os.urandom(8), "big"))
        _local.rng = rng
    return f"{rng.getrandbits(nbytes * 8):0{nbytes * 2}x}"


@dataclasses.dataclass
class Span:
    """One completed (or in-flight) operation in a trace tree."""

    trace_id: str
    span_id: str
    parent_id: str
    name: str
    start: float           # epoch seconds (wall clock, for display)
    duration_ms: float = 0.0
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    error: str = ""

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "start": self.start,
            "durationMs": round(self.duration_ms, 3),
        }
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.error:
            out["error"] = self.error
        return out


def _stack() -> List[Span]:
    st = getattr(_local, "span_stack", None)
    if st is None:
        st = []
        _local.span_stack = st
    return st


def current_span() -> Optional[Span]:
    st = _stack()
    return st[-1] if st else None


def current_trace_id() -> str:
    sp = current_span()
    return sp.trace_id if sp is not None else ""


class span:
    """Context manager opening one span. The outermost span on a thread
    starts a fresh trace id; nested spans become its children. Extra
    keyword arguments become span attributes (shown in /api/traces)."""

    def __init__(self, name: str, **attrs: Any):
        self.name = name
        self.attrs = attrs
        self.span: Optional[Span] = None
        self._t0 = 0.0

    def __enter__(self) -> Span:
        parent = current_span()
        sp = Span(
            trace_id=parent.trace_id if parent else _new_id(8),
            span_id=_new_id(4),
            parent_id=parent.span_id if parent else "",
            name=self.name,
            start=time.time(),
            attrs=dict(self.attrs),
        )
        _stack().append(sp)
        self._t0 = time.monotonic()
        self.span = sp
        if _enabled:
            depth = len(_stack()) - 1
            _logger.info("%s[%d]ENTER: %s", "  " * depth, depth, self.name)
        return sp

    def __exit__(self, exc_type, exc, _tb) -> None:
        sp = self.span
        assert sp is not None
        sp.duration_ms = (time.monotonic() - self._t0) * 1e3
        if exc is not None:
            sp.error = f"{type(exc).__name__}: {exc}"
        st = _stack()
        if st and st[-1] is sp:
            st.pop()
        # configure(span_buffer=0) turns buffering off (trace ids still flow
        # into log records) — no cross-thread lock traffic for data nothing
        # serves.
        if _spans.maxlen:
            with _spans_lock:
                _spans.append(sp)
        if _enabled:
            depth = len(st)
            _logger.info("%s[%d]EXIT:  %s (%.1fms)", "  " * depth, depth,
                         sp.name, sp.duration_ms)


def recent_spans(limit: int = 0) -> List[Dict[str, Any]]:
    """Completed spans, newest first (the /api/traces body)."""
    with _spans_lock:
        items = list(_spans)
    items.reverse()
    if limit > 0:
        items = items[:limit]
    return [sp.to_dict() for sp in items]


def clear_spans() -> None:
    """Test hook: empty the ring buffer."""
    with _spans_lock:
        _spans.clear()


def traced(fn: F) -> F:
    """Record a span around fn (ref: tracey.New Enter/Exit defers). The
    nested ENTER/EXIT log stream additionally appears when --trace is on."""

    label = f"{fn.__module__.rsplit('.', 1)[-1]}.{fn.__qualname__}"

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        with span(label):
            return fn(*args, **kwargs)

    return wrapper  # type: ignore[return-value]


class _FilenameFilter(logging.Filter):
    """Attach short source-file tag (ref: logrus filename hook, main.go:27-32)
    plus the active trace id, so every log record written inside a reconcile
    span is correlatable with its /api/traces entry."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.srcfile = f"{record.filename}:{record.lineno}"
        tid = current_trace_id()
        record.trace_id = tid
        record.trace_tag = f"trace={tid} " if tid else ""
        return True


def install_filename_log_format(json_format: bool = False, level: int = logging.INFO) -> None:
    """Configure root logging with source-file + trace-id tags; JSON format
    optional (ref: --json-log-format for Stackdriver, main.go:40-43)."""
    root = logging.getLogger()
    root.setLevel(level)
    handler = logging.StreamHandler()
    handler.addFilter(_FilenameFilter())
    if json_format:
        import json as _json

        class _JsonFormatter(logging.Formatter):
            def format(self, record: logging.LogRecord) -> str:
                out = {
                    "severity": record.levelname,
                    "message": record.getMessage(),
                    "file": getattr(record, "srcfile", ""),
                    "logger": record.name,
                    "timestamp": self.formatTime(record),
                }
                if getattr(record, "trace_id", ""):
                    out["trace"] = record.trace_id
                return _json.dumps(out)

        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(srcfile)s %(trace_tag)s%(message)s"))
    root.handlers[:] = [handler]
