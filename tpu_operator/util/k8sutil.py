"""Cluster connection resolution.

Reference parity: pkg/util/k8sutil/k8sutil.go:41-120 —
``GetClusterConfig`` resolves KUBECONFIG-or-in-cluster credentials
(k8sutil.go:50-74, including the bare-host DNS workaround), plus the
error predicates (:76-82, now in client/errors.py) and cascade-delete
options (:102-110, subsumed by OwnerReferences + foreground deletion).

Resolution order (first match wins):
1. explicit ``--master`` URL (plain or TLS; used by tests and `kubectl proxy`)
2. ``$KUBECONFIG`` / ``--kubeconfig`` YAML (current-context cluster + user)
3. in-cluster service account
   (/var/run/secrets/kubernetes.io/serviceaccount/*)
"""

from __future__ import annotations

import os
from typing import Any, Dict

from tpu_operator.client.rest import RestConfig

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ConfigError(RuntimeError):
    pass


def get_cluster_config(master_url: str = "", kubeconfig_path: str = "") -> RestConfig:
    """ref: GetClusterConfig (k8sutil.go:50-74)."""
    if master_url:
        return RestConfig(host=master_url)
    kubeconfig_path = kubeconfig_path or os.environ.get("KUBECONFIG", "")
    if kubeconfig_path:
        return _from_kubeconfig(kubeconfig_path)
    return _in_cluster()


def _in_cluster() -> RestConfig:
    host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_file = os.path.join(SERVICE_ACCOUNT_DIR, "token")
    ca_file = os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt")
    if not host or not os.path.exists(token_file):
        raise ConfigError(
            "no --master, no KUBECONFIG, and not running in a cluster "
            "(service account token missing)"
        )
    with open(token_file, encoding="utf-8") as f:
        token = f.read().strip()
    return RestConfig(
        host=f"https://{host}:{port}",
        bearer_token=token,
        ca_cert_file=ca_file if os.path.exists(ca_file) else "",
    )


def _from_kubeconfig(path: str) -> RestConfig:
    import yaml

    with open(path, encoding="utf-8") as f:
        doc: Dict[str, Any] = yaml.safe_load(f) or {}

    def by_name(section: str, name: str) -> Dict[str, Any]:
        for entry in doc.get(section) or []:
            if entry.get("name") == name:
                return entry.get(section.rstrip("s"), {}) or {}
        return {}

    current = doc.get("current-context", "")
    context = by_name("contexts", current)
    cluster = by_name("clusters", context.get("cluster", ""))
    user = by_name("users", context.get("user", ""))

    host = cluster.get("server", "")
    if not host:
        raise ConfigError(f"kubeconfig {path}: no server for context {current!r}")
    return RestConfig(
        host=host,
        bearer_token=user.get("token", ""),
        ca_cert_file=cluster.get("certificate-authority", ""),
        client_cert_file=user.get("client-certificate", ""),
        client_key_file=user.get("client-key", ""),
        insecure_skip_tls_verify=bool(cluster.get("insecure-skip-tls-verify", False)),
    )


def must_new_kube_client(master_url: str = "", kubeconfig_path: str = ""):
    """Build the full typed clientset (ref: MustNewKubeClient, k8sutil.go:84-89)."""
    from tpu_operator.client.rest import Clientset

    return Clientset(get_cluster_config(master_url, kubeconfig_path))
