"""Named yield points for deterministic interleaving tests.

Production code marks the handful of scheduling-sensitive spots —
"worker popped its task", "close drained the queue" — with
``pause("module.point")``. In production the call is a single global
read and a falsy branch (no lock, no allocation); under the
deterministic interleaving harness (``tpu_operator/testing/schedules.py``)
an installed hook turns each point into a scheduling decision, so a
test can drive two real threads through every interleaving of the
marked windows instead of hoping a soak happens to hit the bad one.

Kept in util/ (stdlib-only, zero dependencies) so payload- and
store-side modules can carry yield points without importing the test
harness; only the harness ever installs a hook.
"""

from __future__ import annotations

from typing import Callable, Optional

Hook = Callable[[str], None]

_hook: Optional[Hook] = None


def pause(name: str) -> None:
    """Yield point ``name``: a no-op unless a harness installed a hook."""
    hook = _hook
    if hook is not None:
        hook(name)


def install(hook: Hook) -> None:
    """Install the harness hook. One at a time: overlapping harnesses
    would interleave each other's schedules into nonsense."""
    global _hook
    if _hook is not None:
        raise RuntimeError("a yield-point hook is already installed")
    _hook = hook


def uninstall() -> None:
    global _hook
    _hook = None


def installed() -> bool:
    return _hook is not None
