"""Runtime job-lifecycle witness: tracked containers that prove per-job
state dies with the job.

The operator's most recurring bug class is per-job state that outlives
the job: leaked event-dedup entries (PR 1), unbounded ``tpujob_queue_depth``
label series (PR 7), metric series only pruned on deletion after a PR 9
fix — each found by hand. This module is the lockdep of that bug class:
every container keyed by job identity (the ones carrying a ``# per-job:``
annotation, which the ``lifecycle`` analyzer rule enforces) is created
through the :func:`track` factory with a stable class key
("Controller.jobs"). When the witness is enabled, the factory returns a
registered subclass of the raw container; the controller then
:func:`sweep`-s the registry on every job deletion with the job's
identity tokens, and any tracked container still holding a matching
entry is a leak — recorded in a process-global violation list (the
conftest autouse fixture fails the owning test; the churn soak in
``bench.py --churn`` fails the gate).

Cost model, same contract as :mod:`tpu_operator.util.lockdep`:
**disabled (default), the factories return the raw builtin
containers** — zero overhead, one branch at construction. Enabled
(``TPUJOB_JOBLIFE=1``, or :func:`enable` before the containers are
constructed — tests/conftest.py does this for the whole suite), the
containers are plain subclasses (no per-operation cost); the only work
is the O(total tracked entries) scan per job deletion.

Identity tokens and matching: a deleted job is described by its
reconcile key (``"ns/name"``), its ``(namespace, name)`` tuple, and its
UID when known. A container entry leaks when its key equals a token, or
is a tuple whose leading elements equal a tuple token — which covers
every per-job keying shape in the tree: ``key``-keyed maps (controller,
fleet scheduler, deadline manager, remediation tracker), ``(namespace,
name)``-keyed maps (statusserver heartbeats), and ``(namespace, name,
reason, message)``-keyed caches (event dedup).

Epochs keep the registry honest across a long pytest session: a test's
sweep must not report residue from a *previous* test's abandoned
controller (same job names recur constantly), so the conftest fixture
bumps the epoch before every test and :func:`sweep`/:func:`counts` only
see containers constructed in the current epoch.

Violations accumulate (``violations()``) rather than raise: the sweep
runs inside the reconcile worker's broad try/except, where a raise would
be swallowed into a requeue loop — exactly the lockdep lesson.
"""

from __future__ import annotations

import collections
import os
import threading
import weakref
from typing import Any, Dict, Iterable, List, Optional, Tuple

_enabled = os.environ.get("TPUJOB_JOBLIFE", "") not in ("", "0", "false")

# The witness's own state is guarded by one RAW lock (never witnessed /
# never lockdep-instrumented: the watcher must not watch itself).
_state_lock = threading.Lock()
_containers: "weakref.WeakSet" = weakref.WeakSet()  # guarded-by: _state_lock
_violations: List[str] = []                         # guarded-by: _state_lock
_epoch = 0                                          # guarded-by: _state_lock


def enable(on: bool = True) -> None:
    """Turn the witness on for containers constructed AFTER this call."""
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def new_epoch() -> int:
    """Start a fresh tracking epoch (the conftest fixture calls this per
    test): sweeps and counts only see containers constructed after the
    bump, so one test's abandoned state never bleeds into the next
    test's verdict. Returns the new epoch id."""
    global _epoch
    with _state_lock:
        _epoch += 1
        return _epoch


def current_epoch() -> int:
    """The live epoch id — sweep owners (the controller) capture it at
    construction and pass it back to :func:`sweep`, so a sweeper thread
    lingering from a previous epoch (an abandoned test's worker draining
    its last deletion) can never charge a violation to containers of the
    epoch that replaced it."""
    with _state_lock:
        return _epoch


def reset() -> None:
    """Test hook: drop recorded violations and start a new epoch."""
    global _epoch
    with _state_lock:
        del _violations[:]
        _epoch += 1


def violations() -> List[str]:
    with _state_lock:
        return list(_violations)


def violation_count() -> int:
    with _state_lock:
        return len(_violations)


def report() -> str:
    """Human-readable dump of every recorded violation."""
    with _state_lock:
        if not _violations:
            return "joblife: no per-job state leaks"
        return "\n\n".join(_violations)


def record_violation(message: str) -> None:
    """Record an externally detected lifecycle violation (the controller
    uses this for metric series that outlive a deleted job — the metric
    registry is scanned through :meth:`Metrics.job_series`, not through
    a tracked container)."""
    with _state_lock:
        _violations.append(message)


# --- factories ---------------------------------------------------------------

class _TrackedDict(dict):
    """Plain dict, weakref-able and registered under a class key.
    Identity-hashed so the weak registry can hold it (dicts are
    unhashable; these containers are registry members, never keys)."""

    __hash__ = object.__hash__


class _TrackedOrderedDict(collections.OrderedDict):
    """OrderedDict variant (LRU caches: move_to_end/popitem survive)."""

    __hash__ = object.__hash__


class _TrackedSet(set):
    """Set variant."""

    __hash__ = object.__hash__


_KINDS = {
    "dict": (dict, _TrackedDict),
    "ordered": (collections.OrderedDict, _TrackedOrderedDict),
    "set": (set, _TrackedSet),
}


def track(name: str, kind: str = "dict") -> Any:
    """A container registered for deletion sweeps under ``name``
    ("Class._attr" — the same key the ``# per-job:`` annotation sits
    on). Returns the RAW builtin when the witness is off."""
    raw_cls, tracked_cls = _KINDS[kind]
    if not _enabled:
        return raw_cls()
    obj = tracked_cls()
    with _state_lock:
        obj._joblife_name = name
        obj._joblife_epoch = _epoch
        _containers.add(obj)
    return obj


def _live() -> List[Any]:
    with _state_lock:
        epoch = _epoch
        return [c for c in _containers
                if getattr(c, "_joblife_epoch", -1) == epoch]


# --- sweeps ------------------------------------------------------------------

def _matches(key: Any, token: Any) -> bool:
    if key == token:
        return True
    if isinstance(key, tuple) and isinstance(token, tuple) \
            and len(key) >= len(token):
        return tuple(key[:len(token)]) == token
    return False


_SCAN_ABANDONED = object()


def _scan(container: Any, tokens: Tuple[Any, ...]) -> Any:
    """Residual keys of one container, resilient to concurrent mutation
    (other jobs' state legitimately churns while we scan). Returns the
    sentinel ``_SCAN_ABANDONED`` when the container would not hold still
    — the caller reports it rather than silently vouching "clean" for a
    container the witness never actually saw."""
    import time as _time
    for attempt in range(5):
        try:
            return [k for k in list(container)
                    if any(_matches(k, t) for t in tokens)]
        except RuntimeError:  # size changed mid-list(); retry
            if attempt < 4:
                _time.sleep(0.001)
    return _SCAN_ABANDONED


def residuals(tokens: Iterable[Any]) -> List[Tuple[str, Any]]:
    """(container name, residual key) pairs matching ``tokens`` across
    every live tracked container — the read-only form of :func:`sweep`.
    An unscannable container reports the abandonment sentinel as its
    residual key."""
    toks = tuple(tokens)
    out: List[Tuple[str, Any]] = []
    for container in _live():
        found = _scan(container, toks)
        if found is _SCAN_ABANDONED:
            out.append((container._joblife_name, _SCAN_ABANDONED))
            continue
        for k in found:
            out.append((container._joblife_name, k))
    return out


def sweep(tokens: Iterable[Any], where: str = "",
          epoch: Optional[int] = None) -> List[str]:
    """Assert no tracked container still holds an entry for the job
    described by ``tokens`` (its reconcile key, its ``(namespace, name)``
    tuple, its UID). Each residual entry is a leak: recorded in the
    violation list and returned. Call AFTER the deletion path's cleanup
    has run — anything still matching outlived the job.

    ``epoch`` is the sweeper's capture of :func:`current_epoch` at
    construction: when it no longer matches, the sweeper outlived its
    epoch (an abandoned harness's worker draining a last deletion) and
    the sweep is skipped — its verdict would be about containers it
    never owned."""
    if epoch is not None:
        with _state_lock:
            if epoch != _epoch:
                return []
    found = residuals(tokens)
    if not found:
        return []
    out = []
    for name, k in found:
        if k is _SCAN_ABANDONED:
            out.append(
                f"joblife: sweep could not scan {name} after "
                f"{where or 'job deletion'} — the container never held "
                f"still across 5 attempts; its leak verdict is UNKNOWN, "
                f"which the witness refuses to report as clean")
            continue
        out.append(
            f"joblife: per-job state leak — {name} still holds "
            f"{k!r} after {where or 'job deletion'} (every `# per-job:` "
            f"container must drop its entries on the delete path)")
    with _state_lock:
        _violations.extend(out)
    return out


def counts() -> Dict[str, int]:
    """Live entry count per tracked container name, summed over
    instances (the churn soak's flatness probe)."""
    out: Dict[str, int] = {}
    for container in _live():
        name = container._joblife_name
        out[name] = out.get(name, 0) + len(container)
    return out


def total_entries() -> int:
    return sum(counts().values())
