"""Small shared utilities.

Reference parity: pkg/util/util.go:
- ``rand_string`` ← RandString (util.go:58-74): DNS-safe lowercase suffixes
  for runtime ids and pod names. The reference seeds math/rand with
  time.Now; here the module-level RNG is seeded per-process and injectable
  for tests.
- ``pformat`` ← Pformat (util.go:33-44): pretty JSON for log lines.
- ``get_operator_namespace`` ← the KUBEFLOW_NAMESPACE env lookup
  (util.go:29, server.go:61-65) — renamed to TPU_OPERATOR_NAMESPACE with the
  downward-API ``MY_POD_NAMESPACE`` fallback the chart sets
  (build/chart/.../deployment.yaml:24-37).
"""

from __future__ import annotations

import datetime
import json
import os
import random
import string
import time
from typing import Any, Optional

# DNS-1035-safe alphabet (ref: util.go:55 uses lowercase letters+digits; we
# keep letters-only first char responsibility at call sites).
_ALPHABET = string.ascii_lowercase + string.digits

_rng = random.Random()


def seed(n: int) -> None:
    """Deterministic randomness for tests."""
    _rng.seed(n)


def rand_string(n: int) -> str:
    """Random DNS-safe string of length n (ref: util.go:58-74)."""
    return "".join(_rng.choice(_ALPHABET) for _ in range(n))


def pformat(value: Any) -> str:
    """Pretty-print a value as indented JSON, falling back to repr
    (ref: util.go:33-44 marshals with indent and falls back to %+v)."""
    try:
        return json.dumps(value, indent=2, sort_keys=True, default=str)
    except (TypeError, ValueError):
        return repr(value)


def now_rfc3339() -> str:
    """Current UTC time as RFC3339 with fractional seconds — the timestamp
    format for status.phaseTimeline / lastHeartbeat / Events. Fractional
    precision matters: phase transitions in tests are sub-second, and the
    derived durations (statusserver.derived_durations) subtract these."""
    return format_rfc3339(time.time())


def format_rfc3339(epoch: float) -> str:
    """Epoch seconds → the operator's RFC3339 form (UTC, fractional
    seconds) — the inverse of :func:`parse_rfc3339`, used to stamp
    computed future times (``status.backoffUntil``)."""
    return (datetime.datetime.fromtimestamp(epoch, datetime.timezone.utc)
            .strftime("%Y-%m-%dT%H:%M:%S.%fZ"))


def parse_rfc3339(value: str) -> Optional[float]:
    """RFC3339 string (with or without fractional seconds) → epoch seconds;
    None when empty/unparseable. Tolerant of both forms because K8s stamps
    whole seconds (creationTimestamp) while the operator stamps micros."""
    if not value:
        return None
    for fmt in ("%Y-%m-%dT%H:%M:%S.%fZ", "%Y-%m-%dT%H:%M:%SZ"):
        try:
            dt = datetime.datetime.strptime(value, fmt)
            return dt.replace(tzinfo=datetime.timezone.utc).timestamp()
        except ValueError:
            continue
    return None


def get_operator_namespace() -> str:
    """Namespace the operator watches/records events in.

    Resolution order: TPU_OPERATOR_NAMESPACE env (ref: KUBEFLOW_NAMESPACE,
    util.go:29) → downward-API MY_POD_NAMESPACE (chart deployment.yaml:24-31)
    → "default" (ref: server.go:61-65).
    """
    return (
        os.environ.get("TPU_OPERATOR_NAMESPACE")
        or os.environ.get("MY_POD_NAMESPACE")
        or "default"
    )
