"""Runtime lockdep witness: instrumented locks that prove order discipline.

Linux lockdep's core idea, stdlib-only: every lock the operator creates
goes through the factories here (``lock`` / ``rlock`` / ``condition``)
with a stable *class key* ("FleetScheduler._lock"). When the witness is
enabled, acquiring lock B while holding lock A records the directed edge
A→B in one process-global order graph; the first acquisition that would
close a cycle (B held somewhere while A is acquired) is a potential
deadlock and raises :class:`LockOrderError` carrying BOTH witness
stacks — the acquisition that recorded the forward edge and the one
attempting the inversion — so the report reads like a lockdep splat,
not a post-mortem guess.

Keys name lock *classes*, not instances (all ``FleetScheduler`` objects
share one node), which is what makes the graph meaningful across a
fleet of per-job objects — the same choice lockdep makes. Consequences:

- Re-acquiring the *same object* is reentrancy (fine for rlocks; an
  immediate self-deadlock error for plain locks — the thread would
  block on itself forever).
- Acquiring a *different instance* of the same key while one is held
  records the self-edge ``K→K``: nesting two instances of one lock
  class has no defined order and deadlocks the moment two threads nest
  them oppositely, so it is reported as an inversion outright.
- ``Condition.wait`` releases the underlying lock: the witness pops it
  from the thread's held set for the duration of the wait and re-checks
  order on re-acquisition, so parking in a wait never fabricates edges.

Cost model: **disabled (default), the factories return the raw
``threading`` primitives** — zero per-acquisition overhead, the only
cost is one branch at construction. Enabled (``TPUJOB_LOCKDEP=1``, or
``enable()`` before the locks are constructed — tests/conftest.py does
this for the whole suite), every acquisition pays a thread-local list
scan plus, for never-before-seen edges only, a stack capture and a
cycle check. The chaos soak, the fleet bench harnesses, and every unit
test thereby double as deadlock detectors at the cost of a few percent
of test wall time.

Violations both raise at the offending acquisition *and* accumulate in
a process-global list (``violations()``): controller worker threads
catch broad exceptions by design (a reconcile error is a requeue, not a
crash), so the raise alone could be swallowed — the conftest fixture
asserts the list stayed empty after every test.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Any, Dict, List, Optional, Tuple


class LockOrderError(AssertionError):
    """A lock acquisition that inverts the witnessed global order."""


_enabled = os.environ.get("TPUJOB_LOCKDEP", "") not in ("", "0", "false")

# The witness's own state is guarded by one RAW lock (never witnessed:
# the watcher must not watch itself).
_state_lock = threading.Lock()
_edges: Dict[Tuple[str, str], str] = {}   # (held, acquired) -> witness stack
_violations: List[str] = []               # guarded-by: _state_lock

_tls = threading.local()                  # .held: List[[key, obj_id, count]]


def enable(on: bool = True) -> None:
    """Turn the witness on for locks constructed AFTER this call."""
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Test hook: drop the recorded order graph and violations (held
    sets are per-thread and drain as their with-blocks exit)."""
    with _state_lock:
        _edges.clear()
        del _violations[:]


def violations() -> List[str]:
    with _state_lock:
        return list(_violations)


def violation_count() -> int:
    with _state_lock:
        return len(_violations)


def report() -> str:
    """Human-readable dump of every recorded violation."""
    with _state_lock:
        if not _violations:
            return "lockdep: no lock-order violations"
        return "\n\n".join(_violations)


def edges() -> List[Tuple[str, str]]:
    """The witnessed order graph (introspection/tests)."""
    with _state_lock:
        return sorted(_edges)


def held_keys() -> List[str]:
    """Lock keys the CURRENT thread holds, outermost first."""
    held = getattr(_tls, "held", None)
    return [ent[0] for ent in held] if held else []


# --- factories ---------------------------------------------------------------

def lock(name: str) -> Any:
    """A mutex named ``name`` — ``threading.Lock()`` when the witness is
    off, an instrumented wrapper when it is on."""
    if not _enabled:
        return threading.Lock()
    return _WitnessLock(threading.Lock(), name)


def rlock(name: str) -> Any:
    if not _enabled:
        return threading.RLock()
    return _WitnessRLock(threading.RLock(), name)


def condition(name: str) -> Any:
    """A condition variable whose underlying lock is witnessed under
    ``name`` (waits release it; notify/wait ordering is unchanged)."""
    if not _enabled:
        return threading.Condition()
    return threading.Condition(_WitnessRLock(threading.RLock(), name))


# --- held-set bookkeeping ----------------------------------------------------

def _held() -> List[list]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = []
        _tls.held = held
    return held


def _find_path_locked(src: str, dst: str) -> Optional[List[str]]:
    """DFS: a path src →* dst in the recorded edge graph (call with
    _state_lock held)."""
    if src == dst:
        return [src]
    adj: Dict[str, List[str]] = {}
    for a, b in _edges:
        adj.setdefault(a, []).append(b)
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in adj.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_acquired(obj: Any, key: str, count: int = 1) -> Optional[str]:
    """Record that the current thread now holds ``obj`` (witness key
    ``key``); called AFTER the real acquisition succeeded. Returns an
    inversion report when this acquisition closed a cycle (the caller
    decides whether to raise — it may need to unwind the inner lock
    first), None otherwise."""
    held = _held()
    for ent in held:
        if ent[1] == id(obj):
            ent[2] += count
            return None
    error: Optional[str] = None
    if held:
        # Fast path: every (held, key) edge already witnessed — no stack
        # capture, no graph walk. First sightings pay both, once.
        with _state_lock:
            new_pairs = [(h[0], key) for h in held
                         if (h[0], key) not in _edges]
        if new_pairs:
            here = "".join(traceback.format_stack(limit=16)[:-2])
            with _state_lock:
                for pair in new_pairs:
                    if pair in _edges:
                        continue  # another thread witnessed it first
                    held_key = pair[0]
                    # A cycle exists iff the graph already orders
                    # key before held_key. held_key == key (two
                    # *instances* of one lock class nested) is the
                    # trivial cycle: _find_path_locked(key, key)
                    # returns [key] immediately.
                    path = _find_path_locked(key, held_key)
                    _edges[pair] = here
                    if path is not None and error is None:
                        first_hop = (path[0], path[1]) if len(path) > 1 \
                            else (key, key)
                        prior = _edges.get(first_hop,
                                           "(no recorded stack)")
                        error = (
                            f"lockdep: lock-order inversion — acquiring "
                            f"{key!r} while holding {held_key!r}, but "
                            f"the witnessed order already requires "
                            f"{' -> '.join(path)} -> {held_key}\n"
                            f"--- this acquisition ({held_key} held, "
                            f"taking {key}):\n{here}\n"
                            f"--- prior witness ({first_hop[0]} held, "
                            f"taking {first_hop[1]}):\n{prior}"
                        )
                        _violations.append(error)
    held.append([key, id(obj), count])
    return error


def _note_released(obj: Any, count: int = 1) -> int:
    """Forget ``count`` holds of ``obj`` (0 = all); returns how many
    were recorded."""
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][1] == id(obj):
            had = held[i][2]
            if count and had > count:
                held[i][2] = had - count
                return count
            del held[i]
            return had
    return 0


def _holds(obj: Any) -> bool:
    return any(ent[1] == id(obj) for ent in _held())


# --- instrumented primitives -------------------------------------------------

class _WitnessLock:
    """Plain (non-reentrant) lock with order witnessing."""

    reentrant = False

    def __init__(self, inner: Any, key: str):
        self._inner = inner
        self.key = key

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking and _holds(self):
            err = (f"lockdep: self-deadlock — thread re-acquiring the "
                   f"non-reentrant lock {self.key!r} it already holds")
            with _state_lock:
                _violations.append(err)
            raise LockOrderError(err)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            err = _note_acquired(self, self.key)
            if err is not None:
                # Unwind before raising: acquire() raising from a `with`
                # statement means __exit__ never runs, and a lock left
                # held would wedge every later test behind this one.
                _note_released(self)
                self._inner.release()
                raise LockOrderError(err)
        return ok

    def release(self) -> None:
        _note_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "_WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<lockdep {self.key} {self._inner!r}>"


class _WitnessRLock(_WitnessLock):
    """Reentrant lock with order witnessing. Also implements the
    ``_release_save``/``_acquire_restore``/``_is_owned`` trio
    ``threading.Condition`` borrows from its lock, keeping the held-set
    honest across ``wait()`` (which releases all recursion levels)."""

    reentrant = True

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            err = _note_acquired(self, self.key)
            if err is not None:
                _note_released(self)
                self._inner.release()
                raise LockOrderError(err)
        return ok

    # -- Condition integration -------------------------------------------------

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self) -> Tuple[Any, int]:
        count = _note_released(self, 0)  # wait() drops every level
        return self._inner._release_save(), count

    def _acquire_restore(self, state: Tuple[Any, int]) -> None:
        inner_state, count = state
        self._inner._acquire_restore(inner_state)
        # Re-acquisition after a wait is a fresh acquisition: the held
        # set may have changed while parked, so the order is re-checked.
        # A violation here is recorded (the conftest guard fails the
        # test) but NOT raised: unwinding mid-restore would leave the
        # Condition believing it holds a lock it released.
        _note_acquired(self, self.key, max(1, count))
