"""CLI flags.

Reference parity: cmd/mx-operator/app/options/options.go:23-45. The
reference declared ``--chaos-level`` and ``--gc-interval`` but wired them to
nothing (options.go:40,42 — SURVEY.md quirks); here both are functional:
chaos feeds the fault injector (controller/chaos.py), gc-interval drives the
orphan sweep (controller.run_gc_once).
"""

from __future__ import annotations

import argparse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-operator",
        description="Kubernetes operator for TPU-native JAX training jobs",
    )
    # ref flags (options.go:38-45)
    p.add_argument("--chaos-level", type=int, default=-1,
                   help="DANGEROUS: fault-injection level; >=0 randomly kills "
                        "one managed pod per chaos interval (default: off)")
    p.add_argument("--chaos-interval", type=float, default=30.0,
                   help="seconds between chaos kills when --chaos-level >= 0")
    p.add_argument("--chaos-api-error-rate", type=float, default=0.0,
                   help="DANGEROUS: probability (0-1) of injecting a 429/500 "
                        "ApiError into each of the operator's own API calls "
                        "(FlakyClientset; default: off)")
    p.add_argument("--chaos-api-latency", type=float, default=0.0,
                   help="max seconds of uniform latency injected per API "
                        "call when --chaos-api-error-rate is set")
    p.add_argument("--gc-interval", type=float, default=600.0,
                   help="seconds between orphaned-child GC sweeps")
    p.add_argument("--controller-config-file", default="",
                   help="path to the admin ControllerConfig YAML "
                        "(accelerator → volumes/env injection map)")
    p.add_argument("--json-log-format", action="store_true",
                   help="structured JSON logs (Stackdriver-friendly)")
    p.add_argument("--version", action="store_true", help="print version and exit")
    # connection / runtime flags (the reference hardcoded these or used env)
    p.add_argument("--master", default="",
                   help="apiserver URL override (e.g. http://127.0.0.1:8001)")
    p.add_argument("--kubeconfig", default="",
                   help="kubeconfig path (default: $KUBECONFIG or in-cluster)")
    p.add_argument("--namespace", default="",
                   help="namespace to operate in (default: "
                        "$TPU_OPERATOR_NAMESPACE / $MY_POD_NAMESPACE / default)")
    p.add_argument("--threadiness", type=int, default=1,
                   help="concurrent reconcile workers (ref ran 1; >1 is safe "
                        "here); ignored when --reconcile-shards > 1")
    p.add_argument("--reconcile-shards", type=int, default=1,
                   help="split the reconcile loop into N per-shard workers "
                        "with stable key-hash affinity (one worker owns one "
                        "shard; a job never reconciles concurrently); 1 = "
                        "the single shared workqueue")
    p.add_argument("--status-writeback-qps", type=float, default=0.0,
                   help="global cap on NON-critical status-writeback PUT/s "
                        "(telemetry, replica roll-ups, queue positions); "
                        "phase/attempt transitions always write. 0 = "
                        "unlimited. At ~5k jobs a cap keeps telemetry churn "
                        "from becoming thousands of PUT/s")
    p.add_argument("--slice-inventory", default=None,
                   help="static fleet-scheduler capacity, "
                        "'<resource>:<topology>=<slices>[,...]' (e.g. "
                        "'cloud-tpus.google.com/v4:2x2x4=8'); overrides the "
                        "config file's sliceInventory (an explicit '' "
                        "disables admission control even when the config "
                        "file sets one)")
    p.add_argument("--discover-slice-inventory", action="store_true",
                   help="discover fleet-scheduler slice capacity from a "
                        "live node watch (allocatable TPU resource × "
                        "topology label × slice-id label) instead of a "
                        "static map; capacity changes (node added/removed/"
                        "relabeled) update admission and rebalance the "
                        "queue without an operator restart")
    p.add_argument("--node-debounce-seconds", type=float, default=None,
                   help="debounce window for DISCOVERED capacity shrinks: "
                        "a node NotReady→Ready flap inside the window never "
                        "reaches the fleet scheduler, so admission does not "
                        "churn on kubelet heartbeat blips; growth always "
                        "applies immediately (default: 5.0, or the config "
                        "file's nodeDebounceSeconds; 0 disables)")
    p.add_argument("--resync-period", type=float, default=30.0,
                   help="informer resync/re-list period in seconds")
    p.add_argument("--no-leader-elect", action="store_true",
                   help="skip leader election (single-instance deployments/tests)")
    # Leader-election cadence (reference hardcoded 15/5/3 s, server.go:48-52).
    p.add_argument("--lease-duration", type=float, default=15.0,
                   help="leader-election lease duration in seconds")
    p.add_argument("--renew-deadline", type=float, default=5.0,
                   help="leader-election renew deadline in seconds")
    p.add_argument("--retry-period", type=float, default=3.0,
                   help="leader-election retry period in seconds")
    p.add_argument("--trace", action="store_true",
                   help="function-level call tracing (the go-tracey equivalent)")
    p.add_argument("--trace-buffer", type=int, default=512,
                   help="spans kept in the in-memory ring buffer served at "
                        "GET /api/traces")
    p.add_argument("--status-port", type=int, default=0,
                   help="port for /healthz, /readyz, /metrics, traces, "
                        "heartbeats, and the job dashboard (0 = disabled; "
                        "the chart passes 8080; the reference had none of "
                        "these)")
    p.add_argument("--create-parallelism", type=int, default=None,
                   help="max concurrent child-create RPCs per gang sync "
                        "(pods + services); 1 = sequential (default: 16, or "
                        "the config file's createParallelism). A 256-pod "
                        "gang costs ~N/parallelism create round trips")
    p.add_argument("--advertise-status-url", default="",
                   help="base URL workers reach the status server at (e.g. "
                        "http://tpu-operator.kubeflow:8080); injected into "
                        "pods as TPUJOB_STATUS_URL so payloads post step "
                        "heartbeats (empty = heartbeats off)")
    return p
