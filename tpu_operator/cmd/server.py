"""Server bootstrap: wire config → clients → informers → leader election →
controller.

Reference parity: cmd/mx-operator/app/server.go:54-132 —
cluster config (:70), clients (:155-173), controller-config YAML
(:134-153), informer factory with 30 s resync (:85), leader election on the
``tf-operator`` lock with lease 15 s / renew 5 s / retry 3 s (:48-52,
:106-129), and controller.Run with threadiness 1 on winning (:93-95).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional

from tpu_operator.apis.tpujob.v1alpha1.types import ControllerConfig
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.controller.chaos import ChaosMonkey, FlakyClientset
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.leaderelection import LeaderElector
from tpu_operator.controller.statusserver import StatusServer
from tpu_operator.util import k8sutil, tracing
from tpu_operator.util.util import get_operator_namespace

log = logging.getLogger(__name__)


def read_controller_config(path: str) -> ControllerConfig:
    """ref: readControllerConfig (server.go:134-153)."""
    if not path:
        return ControllerConfig()
    import yaml

    with open(path, encoding="utf-8") as f:
        doc = yaml.safe_load(f) or {}
    return ControllerConfig.from_dict(doc)


def parse_slice_inventory(spec: str) -> dict:
    """``--slice-inventory`` flag form → the config map:
    '<resource>:<topology>=<slices>[,...]' (topology may be empty)."""
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        key, _, count = part.rpartition("=")
        if not key or not count:
            raise ValueError(
                f"bad --slice-inventory entry {part!r} "
                f"(want '<resource>:<topology>=<slices>')")
        if ":" not in key:
            # Demand keys are always '<resource>:<topology>' (topology may
            # be empty, but the colon is structural) — a colon-less key
            # can never match any job and silently disables admission
            # control for that shape.
            raise ValueError(
                f"bad --slice-inventory key {key!r}: want "
                f"'<resource>:<topology>' (use '{key}:=N' for a "
                f"topology-less shape)")
        slices = int(count)
        if slices < 1:
            # A zero/negative capacity would queue every job of this shape
            # forever with no error — the silent failure mode the
            # inventory explicitly rejects for typos.
            raise ValueError(
                f"bad --slice-inventory entry {part!r}: slices must be >= 1")
        out[key] = slices
    return out


def run(opts: Any, clientset: Optional[Any] = None,
        stop_event: Optional[threading.Event] = None) -> None:
    """ref: app.Run (server.go:54-132). ``clientset``/``stop_event`` are
    injectable for tests; production resolves them from flags/env."""
    namespace = opts.namespace or get_operator_namespace()
    if clientset is None:
        clientset = k8sutil.must_new_kube_client(opts.master, opts.kubeconfig)
    config = read_controller_config(opts.controller_config_file)
    if getattr(opts, "advertise_status_url", ""):
        config.status_url = opts.advertise_status_url
    if getattr(opts, "create_parallelism", None) is not None:
        config.create_parallelism = opts.create_parallelism
    if getattr(opts, "slice_inventory", None) is not None:
        # The flag overrides the config file outright; an explicit ''
        # parses to an empty map = admission control off.
        config.slice_inventory = parse_slice_inventory(opts.slice_inventory)
    if getattr(opts, "discover_slice_inventory", False):
        config.discover_slice_inventory = True
    if getattr(opts, "node_debounce_seconds", None) is not None:
        config.node_debounce_seconds = max(0.0, opts.node_debounce_seconds)
    tracing.configure(span_buffer=getattr(opts, "trace_buffer",
                                          tracing.DEFAULT_SPAN_BUFFER))
    stop_event = stop_event or threading.Event()

    api_error_rate = getattr(opts, "chaos_api_error_rate", 0.0)
    if api_error_rate > 0:
        # API-level chaos: the controller (and its informers) see injected
        # 429/500s + latency on every call; the leader elector and chaos
        # monkey below share the same flaky view — production-shaped misery.
        clientset = FlakyClientset(
            clientset, error_rate=api_error_rate,
            max_latency=getattr(opts, "chaos_api_latency", 0.0))
        log.warning("chaos: flaky clientset enabled (error rate %.0f%%)",
                    api_error_rate * 100)

    factory = SharedInformerFactory(clientset, namespace,
                                    resync_period=opts.resync_period)
    controller = Controller(
        clientset, factory, config, namespace,
        shards=getattr(opts, "reconcile_shards", 1) or 1,
        writeback_qps=getattr(opts, "status_writeback_qps", 0.0) or 0.0)
    # Late-bind the metrics registry into the chaos wrapper and the REST
    # transport (both exist before the controller's registry does).
    if isinstance(clientset, FlakyClientset):
        clientset.metrics = controller.metrics
    rest = getattr(clientset, "rest", None)
    if rest is not None and getattr(rest, "metrics", None) is None:
        rest.metrics = controller.metrics

    # Observability binds before leader election: standbys must answer
    # kubelet probes too (statusserver.py; the reference had no probes,
    # metrics, or working dashboard — SURVEY.md §5).
    status: Optional[StatusServer] = None
    if getattr(opts, "status_port", 0):
        status = StatusServer(opts.status_port, metrics=controller.metrics)
        status.start()

    def start_leading(leading_stop: threading.Event) -> None:
        # Auxiliary loops ride the leadership scope, like controller.Run
        # (ref: server.go:93-95).
        if status is not None:
            status.set_controller(controller)
        threading.Thread(target=controller.run_gc_loop,
                         args=(opts.gc_interval, leading_stop),
                         daemon=True, name="gc").start()
        if opts.chaos_level >= 0:
            monkey = ChaosMonkey(clientset, namespace, opts.chaos_level,
                                 opts.chaos_interval,
                                 recorder=controller.recorder,
                                 metrics=controller.metrics)
            threading.Thread(target=monkey.run, args=(leading_stop,),
                             daemon=True, name="chaos").start()
        controller.run(opts.threadiness, leading_stop)

    try:
        if opts.no_leader_elect:
            start_leading(stop_event)
            return

        elector = LeaderElector(
            clientset, namespace,
            lease_duration=opts.lease_duration,
            renew_deadline=opts.renew_deadline,
            retry_period=opts.retry_period,
        )
        elector.run(on_started_leading=start_leading, stop_event=stop_event)
        if not stop_event.is_set():
            # Lost the lease (ref: OnStoppedLeading → fatal, server.go:98-102):
            # exit nonzero so the Deployment restarts a fresh instance.
            raise RuntimeError("leader election lost; exiting for restart")
    finally:
        if status is not None:
            status.stop()
