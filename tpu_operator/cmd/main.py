"""Process entry point: ``python -m tpu_operator.cmd.main``.

Reference parity: cmd/mx-operator/main.go:34-49 — flag parsing, the
filename-tagging log hook (main.go:27-32), optional JSON log format for
Stackdriver (main.go:40-43), ``--version`` (main.go:44-46 → version.go), and
handoff to app.Run.
"""

from __future__ import annotations

import logging
import sys

from tpu_operator import version
from tpu_operator.cmd.options import build_parser
from tpu_operator.cmd.server import run
from tpu_operator.util import tracing

log = logging.getLogger(__name__)


def main(argv=None) -> int:
    opts = build_parser().parse_args(argv)
    if opts.version:
        print(version.info())
        return 0
    tracing.install_filename_log_format(json_format=opts.json_log_format)
    if opts.trace:
        tracing.enable()
    log.info("tpu-operator %s starting", version.VERSION)
    try:
        run(opts)
    except KeyboardInterrupt:
        log.info("interrupted; shutting down")
    except Exception as e:  # noqa: BLE001 — fatal startup/runtime error
        log.error("fatal: %s", e)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
