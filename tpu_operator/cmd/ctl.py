"""``tpujobctl`` — user-facing CLI for TPUJobs.

The reference offered no tooling beyond raw ``kubectl create -f`` plus
reading status YAML by eye (README.md:96-121). This is the quality-of-life
layer on top of the same API surface: submit manifests, list jobs with their
phase roll-up, describe one job with per-replica states and its recorded
Events, and delete. Talks straight to the apiserver through the in-repo REST
client, so it works against any cluster ``kubectl`` does (kubeconfig /
in-cluster / --master), and against the in-repo test apiserver.

    tpujobctl submit -f examples/tpujob-linear.yml
    tpujobctl list
    tpujobctl describe cifar10
    tpujobctl delete cifar10

Observability commands talk to the operator's STATUS server (the /api
surface the controller serves, not the apiserver) via ``--status-url``
or ``$TPUJOB_STATUS_URL``:

    tpujobctl timeline cifar10           # unified per-job span timeline
    tpujobctl timeline cifar10 --chrome  # perfetto-loadable trace JSON
    tpujobctl profile cifar10 --steps 16 # request a raw-lap deep capture
    tpujobctl top                        # one-screen fleet rollup
"""

from __future__ import annotations

import argparse
import calendar
import json
import os
import sys
import time
import uuid
from typing import Any, Dict, List

from tpu_operator import version as version_mod
from tpu_operator.apis.tpujob.v1alpha1.types import (
    DEFAULT_AUTOTUNE_MAX_DEPTH,
    DEFAULT_AUTOTUNE_MIN_DEPTH,
    DEFAULT_AUTOTUNE_WINDOW_STEPS,
    PROFILE_ANNOTATION,
)
from tpu_operator.client import errors

# The ``tpujobctl top`` column contract, pinned by tests: reordering or
# renaming a column is an interface change, not a cosmetic one.
TOP_COLUMNS = ["NAME", "PHASE", "QUEUE", "POS", "GOODPUT", "STRAGGLER",
               "DURABLE", "STEP", "RESTARTS"]

# Commands served entirely by the status server — no apiserver client.
STATUS_ONLY_COMMANDS = ("timeline", "top")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpujobctl",
        description="Manage TPUJobs (submit / list / describe / delete)",
    )
    p.add_argument("--master", default="", help="apiserver URL override")
    p.add_argument("--kubeconfig", default="", help="kubeconfig path")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--status-url", default="",
                   help="operator status-server URL (default "
                        "$TPUJOB_STATUS_URL or http://localhost:8080)")
    p.add_argument("--version", action="store_true", help="print version and exit")
    sub = p.add_subparsers(dest="command")

    sp = sub.add_parser("submit", help="create TPUJob(s) from a manifest")
    sp.add_argument("-f", "--filename", required=True,
                    help="YAML manifest (may contain multiple documents)")

    sub.add_parser("list", help="list TPUJobs")

    gp = sub.add_parser("get", help="print one TPUJob")
    gp.add_argument("name")
    gp.add_argument("-o", "--output", choices=("yaml", "json"), default="yaml")

    dp = sub.add_parser("describe",
                        help="job summary: replicas, statuses, events")
    dp.add_argument("name")

    rp = sub.add_parser("delete", help="delete a TPUJob (children follow via GC)")
    rp.add_argument("name")

    tl = sub.add_parser("timeline",
                        help="unified span timeline for one job")
    tl.add_argument("name")
    tl.add_argument("--chrome", action="store_true",
                    help="emit Chrome trace-event JSON (perfetto-loadable)"
                         " instead of the table")

    pr = sub.add_parser("profile",
                        help="request an on-demand deep capture of N raw "
                             "step laps from process 0")
    pr.add_argument("name")
    pr.add_argument("--steps", type=int, default=8)

    sub.add_parser("top", help="one-screen fleet rollup "
                               "(goodput, queues, stragglers)")
    return p


def _clientset(opts):
    from tpu_operator.util import k8sutil

    return k8sutil.must_new_kube_client(opts.master, opts.kubeconfig)


def _age(obj: Dict[str, Any]) -> str:
    ts = (obj.get("metadata") or {}).get("creationTimestamp", "")
    if not ts:
        return "-"
    try:
        created = calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
    except ValueError:
        return "-"
    seconds = max(0, int(time.time() - created))
    for unit, div in (("d", 86400), ("h", 3600), ("m", 60)):
        if seconds >= div:
            return f"{seconds // div}{unit}"
    return f"{seconds}s"


def _print_table(rows: List[List[str]], header: List[str]) -> None:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    for row in [header] + rows:
        print("  ".join(str(c).ljust(w) for c, w in zip(row, widths)).rstrip())


def cmd_submit(cs, opts) -> int:
    import yaml

    with open(opts.filename, encoding="utf-8") as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    if not docs:
        print(f"no documents in {opts.filename}", file=sys.stderr)
        return 1
    submitted = 0
    for doc in docs:
        if doc.get("kind") != "TPUJob":
            print(f"skipping non-TPUJob document kind={doc.get('kind')!r}",
                  file=sys.stderr)
            continue
        ns = (doc.get("metadata") or {}).get("namespace") or opts.namespace
        created = cs.tpujobs.create(ns, doc)
        print(f"tpujob {ns}/{created['metadata']['name']} created")
        submitted += 1
    if not submitted:
        print(f"no TPUJob documents in {opts.filename}", file=sys.stderr)
        return 1
    return 0


def cmd_list(cs, opts) -> int:
    jobs = cs.tpujobs.list(opts.namespace)
    rows = []
    for j in jobs:
        status = j.get("status") or {}
        spec = j.get("spec") or {}
        replicas = ",".join(
            f"{rs.get('tpuReplicaType', 'WORKER')}×{rs.get('replicas', 0)}"
            for rs in spec.get("replicaSpecs", []))
        rows.append([
            j["metadata"]["name"],
            status.get("phase", ""),
            status.get("state", ""),
            str(status.get("attempt", 0)),
            replicas,
            _age(j),
        ])
    _print_table(rows, ["NAME", "PHASE", "STATE", "ATTEMPT", "REPLICAS", "AGE"])
    return 0


def cmd_get(cs, opts) -> int:
    job = cs.tpujobs.get(opts.namespace, opts.name)
    if opts.output == "json":
        print(json.dumps(job, indent=2))
    else:
        import yaml

        print(yaml.safe_dump(job, default_flow_style=False, sort_keys=False),
              end="")
    return 0


def cmd_describe(cs, opts) -> int:
    job = cs.tpujobs.get(opts.namespace, opts.name)
    md, spec = job["metadata"], job.get("spec") or {}
    status = job.get("status") or {}
    print(f"Name:       {md['name']}")
    print(f"Namespace:  {md.get('namespace', opts.namespace)}")
    print(f"Phase:      {status.get('phase', '')}")
    print(f"State:      {status.get('state', '')}")
    print(f"Attempt:    {status.get('attempt', 0)} / "
          f"maxRestarts {spec.get('maxRestarts', '')}")
    if spec.get("tpuTopology"):
        print(f"Topology:   {spec['tpuTopology']}")
    if spec.get("checkpointDir"):
        print(f"Checkpoint: {spec['checkpointDir']}")
    if spec.get("profileDir"):
        print(f"Profile:    {spec['profileDir']}")
    print("Replicas:")
    for rs in spec.get("replicaSpecs", []):
        print(f"  {rs.get('tpuReplicaType', 'WORKER')}: "
              f"{rs.get('replicas', 0)} × port {rs.get('tpuPort', '')}")
    # Elastic gangs: the attempt's granted world vs the spec'd range,
    # resize accounting, and the straggler-remediation audit trail.
    el_spec = spec.get("elastic") or {}
    el = status.get("elastic") or {}
    if el_spec or el:
        hi = el.get("maxSlices") or el_spec.get("maxSlices") \
            or spec.get("numSlices", 1)
        lo = el.get("minSlices") or el_spec.get("minSlices", 1)
        line = (f"Elastic:    {el.get('slices', '?')}/{hi} slices "
                f"(range {lo}-{hi}, resizes {el.get('resizes', 0)}, "
                f"policy {el_spec.get('stragglerPolicy', 'none')})")
        direction = el.get("lastResizeDirection")
        if direction:
            line += f" — last resize {direction}"
        print(line)
        for r in (el.get("remediations") or [])[-5:]:
            node = f" off node {r['node']}" if r.get("node") else ""
            print(f"Remediated: attempt {r.get('attempt', 0)}: "
                  f"{r.get('policy', '?')} process "
                  f"{r.get('processId', '?')}{node} ({r.get('time', '')})")
    # Serving mode: readiness, traffic, tail latency, the loaded snapshot
    # step, and the hot-reload trail (spec half = the scaling contract,
    # status half = the controller's fleet aggregate).
    sv_spec = spec.get("serving") or {}
    sv = status.get("serving") or {}
    if spec.get("mode") == "serve" or sv_spec or sv:
        total = sv.get("replicas") or sum(
            rs.get("replicas", 0) for rs in spec.get("replicaSpecs", [])
            if str(rs.get("tpuReplicaType", "WORKER")).upper() == "WORKER")
        line = f"Serving:    {sv.get('replicasReady', 0)}/{total} ready"
        if sv.get("desiredReplicas") is not None:
            line += f" (desired {sv['desiredReplicas']}"
            if sv_spec:
                line += (f", range {sv_spec.get('minReplicas', 1)}-"
                         f"{sv_spec.get('maxReplicas', total)}")
            line += ")"
        if sv.get("requestsPerSecond") is not None:
            line += f", {sv['requestsPerSecond']:.1f} req/s"
        if sv.get("tokensPerSecond") is not None:
            line += f", {sv['tokensPerSecond']:.0f} tok/s"
        if sv.get("p95LatencySeconds") is not None:
            line += f", p95 {sv['p95LatencySeconds'] * 1000:.1f} ms"
        print(line)
        # The backpressure line: queued demand + KV page-pool pressure
        # (the paged-decode admission signals).
        if sv.get("queueDepth") is not None \
                or sv.get("kvCacheUtilization") is not None:
            parts = []
            if sv.get("queueDepth") is not None:
                parts.append(f"queue depth {sv['queueDepth']}")
            if sv.get("kvCacheUtilization") is not None:
                parts.append(
                    f"KV cache {sv['kvCacheUtilization'] * 100:.0f}% held")
            print(f"Backlog:    {', '.join(parts)}")
        if sv.get("loadedStep") is not None or sv.get("reloads"):
            reload_s = f"{sv.get('reloads', 0)} reload(s)"
            if sv.get("time") and sv.get("reloads"):
                reload_s += f", last fold {sv['time']}"
            print(f"Weights:    loaded step "
                  f"{sv.get('loadedStep', '-')} ({reload_s})")
    # Fleet-scheduling state: effective queue/priority, the admission-order
    # position while parked in phase Queued, and — after a scheduler
    # eviction — the reason from the failure ledger.
    sched = {**(spec.get("scheduling") or {}),
             **(status.get("scheduling") or {})}
    queued = status.get("phase") == "Queued"
    if sched or queued:
        line = (f"Scheduling: queue {sched.get('queue', 'default')!r}, "
                f"priority {sched.get('priority', 0)}")
        if queued:
            pos = sched.get("position")
            line += (f" — queued at position {pos}" if pos is not None
                     else " — queued")
        print(line)
    preemptions = [f for f in status.get("failures", [])
                   if f.get("kind") == "preemption"]
    if preemptions:
        last = preemptions[-1]
        print(f"Preempted:  attempt {last.get('attempt', 0)}: "
              f"{last.get('reason', '')} ({last.get('time', '')})")
    # Cooperative drain: the in-flight directive (with its hard-teardown
    # deadline) or the last resolved one (with the step it drained at).
    dr = status.get("drain") or {}
    if dr:
        line = (f"Drain:      {dr.get('state', '?')} — "
                f"{dr.get('reason', '?')} (id {dr.get('id', '?')}, "
                f"attempt {dr.get('attempt', '?')})")
        if dr.get("targetSlices") is not None:
            line += f", target {dr['targetSlices']} slice(s)"
        if dr.get("drainedStep") is not None:
            line += f", drained at step {dr['drainedStep']}"
        if dr.get("state") in ("Requested", "Acked") and dr.get("deadline"):
            line += f", hard teardown at {dr['deadline']}"
        print(line)
    if status.get("backoffUntil"):
        print(f"Backoff:    re-gang parked until {status['backoffUntil']}")
    ck = status.get("checkpoint") or {}
    if ck:
        durable = ck.get("lastCheckpointStep")
        print(f"Durable:    last verified checkpoint step "
              f"{'-' if durable is None else durable} "
              f"(save failures {ck.get('saveFailures', 0)}, "
              f"restore fallbacks {ck.get('restoreFallbacks', 0)})")
    # Remote warm-start store: the spec half (backend/URI) and the status
    # roll-up half (what is actually durable remotely).
    spec_store = spec.get("store") or {}
    st = status.get("store") or {}
    if spec_store or st:
        uploaded = st.get("lastUploadedStep")
        print(f"Store:      {spec_store.get('backend', '?')} "
              f"{spec_store.get('uri', '')} — last uploaded step "
              f"{'-' if uploaded is None else uploaded} "
              f"(upload failures {st.get('uploadFailures', 0)})")
    su = status.get("startup") or {}
    if su:
        stages = " ".join(
            f"{label} {su[key]:.2f}s"
            for label, key in (("rendezvous", "rendezvousSeconds"),
                               ("prefetch", "prefetchSeconds"),
                               ("restore", "restoreSeconds"),
                               ("compile", "compileSeconds"),
                               ("first-step", "firstStepSeconds"))
            if su.get(key) is not None) or "-"
        cache = su.get("cacheHit")
        cache_s = ("warm (compilation cache hit)" if cache
                   else "cold" if cache is not None else "unknown")
        pf = su.get("prefetchHit")
        if pf is not None:
            cache_s += (", prefetch hit" if pf else ", prefetch miss")
        print(f"Startup:    attempt {su.get('attempt', 0)}: {stages} "
              f"[{cache_s}]")
    gp = status.get("goodput") or {}
    if gp.get("ratio") is not None:
        print(f"Goodput:    {100 * gp['ratio']:.1f}% "
              f"(useful {gp.get('usefulStepSeconds', 0):.1f}s / "
              f"wallclock {gp.get('wallclockSeconds', 0):.1f}s)")
    # Data-plane flight recorder: where step time goes (newest digest
    # window from process 0) and any gang member pacing the collective.
    st = status.get("stepTiming") or {}
    if st:
        p50, p95 = st.get("stepP50Seconds"), st.get("stepP95Seconds")
        head = (f"p50 {p50:.4f}s p95 {p95:.4f}s"
                if p50 is not None and p95 is not None else "-")
        print(f"Step:       {head} over {st.get('steps', '?')} steps "
              f"(attempt {st.get('attempt', 0)})")
        phases = st.get("phases") or {}
        if phases:
            print("  Phase         p50          p95          max")
            for key in ("dataWait", "dispatch", "compute", "checkpoint",
                        "host"):
                d = phases.get(key)
                if not d:
                    continue
                print(f"  {key:<12}  {d.get('p50Seconds', 0):>9.6f}s  "
                      f"{d.get('p95Seconds', 0):>9.6f}s  "
                      f"{d.get('maxSeconds', 0):>9.6f}s")
    # Self-tuning data plane: the live knob values (spec half = the
    # requested config, status half = what the runtime is actually doing)
    # and the lifetime adjustment trail.
    dp_spec = spec.get("dataPlane") or {}
    dp = status.get("dataPlane") or {}
    if dp_spec or dp:
        at = dp_spec.get("autotune") or {}
        depth = dp.get("prefetchDepth",
                       dp_spec.get("prefetchDepth", "?"))
        mode = ("auto" if at.get("enabled", bool(at)) or
                dp_spec.get("prefetchDepth", 0) == 0 else "static")
        line = f"DataPlane:  prefetch depth {depth} ({mode}"
        if at:
            # Sparse autotune blocks round-trip what the user wrote, so
            # the display fallbacks must be THE spec defaults (one
            # definition via types.py), not restated literals.
            line += (f", range {at.get('minDepth', DEFAULT_AUTOTUNE_MIN_DEPTH)}-"
                     f"{at.get('maxDepth', DEFAULT_AUTOTUNE_MAX_DEPTH)}, window "
                     f"{at.get('windowSteps', DEFAULT_AUTOTUNE_WINDOW_STEPS)} steps")
        line += ")"
        if dp.get("hostAsync") is not None:
            line += (", host path "
                     + ("async" if dp["hostAsync"] else "inline"))
        if dp.get("checkpointIntervalSteps") is not None:
            line += f", ckpt every {dp['checkpointIntervalSteps']}"
        if dp.get("hostDropped"):
            line += f", host drops {dp['hostDropped']}"
        print(line)
        adj = dp.get("adjustments") or {}
        if any(adj.values()):
            trail = ", ".join(
                f"{knob} +{adj.get(knob + 'Up', 0)}/-"
                f"{adj.get(knob + 'Down', 0)}"
                for knob in ("prefetch", "host", "checkpoint")
                if adj.get(knob + "Up", 0) or adj.get(knob + "Down", 0))
            print(f"Autotuned:  {trail} (attempt {dp.get('attempt', 0)})")
    for s in status.get("stragglers") or []:
        print(f"Straggler:  process {s.get('processId', '?')} p95 "
              f"{s.get('p95Seconds', 0):.3f}s vs gang median "
              f"{s.get('gangMedianSeconds', 0):.3f}s "
              f"({s.get('ratio', 0):.1f}x) at step {s.get('step', '?')}")
    if status.get("failures"):
        print("Failures:")
        for f in status["failures"][-10:]:
            resume = (f" resume@{f['resumeStep']}"
                      if f.get("resumeStep") is not None else "")
            # Elastic jobs: the failed attempt's world size sits next to
            # its resume step — which size ran, which size resumed.
            world = (f" world {f['worldSlices']}"
                     if f.get("worldSlices") is not None else "")
            print(f"  attempt {f.get('attempt', 0)}\t{f.get('kind', '')}\t"
                  f"{f.get('reason', '')}\t{f.get('time', '')}"
                  f"{resume}{world}")
    if status.get("replicaStatuses"):
        print("Replica statuses:")
        for rstat in status["replicaStatuses"]:
            print(f"  {rstat.get('tpuReplicaType', '')}: "
                  f"{rstat.get('state', '')} {rstat.get('replicasStates', {})}")
    try:
        events = cs.events.list(opts.namespace)
    except errors.ApiError:
        events = []
    related = [e for e in events
               if (e.get("involvedObject") or {}).get("name") == opts.name]
    if related:
        print("Events:")
        for e in related[-10:]:
            print(f"  {e.get('type', '')}\t{e.get('reason', '')}\t"
                  f"x{e.get('count', 1)}\t{e.get('message', '')}")
    return 0


def cmd_delete(cs, opts) -> int:
    cs.tpujobs.delete(opts.namespace, opts.name)
    print(f"tpujob {opts.namespace}/{opts.name} deleted")
    return 0


def _status_get(opts, path: str) -> Any:
    """GET a JSON body from the operator's status server."""
    import urllib.request

    base = (opts.status_url or os.environ.get("TPUJOB_STATUS_URL")
            or "http://localhost:8080").rstrip("/")
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _fmt_seconds(value: Any) -> str:
    if value is None:
        return "-"
    v = float(value)
    if v >= 60:
        return f"{v / 60:.1f}m"
    return f"{v:.2f}s"


def cmd_timeline(cs, opts) -> int:
    fmt = "?format=chrome" if opts.chrome else ""
    body = _status_get(
        opts, f"/api/jobs/{opts.namespace}/{opts.name}/timeline{fmt}")
    if opts.chrome:
        # The raw trace-event array: pipe to a file and load in perfetto.
        print(json.dumps(body, indent=1))
        return 0
    spans = body.get("spans") or []
    print(f"Timeline: {body.get('job', '')} "
          f"(phase {body.get('phase', '?')}, {len(spans)} span(s))")
    gp = body.get("goodput") or {}
    if gp.get("ratio") is not None:
        print(f"Goodput:  {100 * float(gp['ratio']):.1f}%")
    if not spans:
        return 0
    t0 = min(sp["start"] for sp in spans)
    rows = []
    for sp in spans:
        attrs = sp.get("attrs") or {}
        detail = " ".join(f"{k}={attrs[k]}" for k in sorted(attrs))
        if sp.get("traceId"):
            detail = (detail + " " if detail else "")                 + f"trace={sp['traceId']}"
        rows.append([
            f"+{sp['start'] - t0:.1f}s",
            _fmt_seconds(sp.get("durationSeconds")),
            sp.get("kind", ""),
            sp.get("name", ""),
            detail,
        ])
    _print_table(rows, ["AT", "DUR", "KIND", "SPAN", "DETAIL"])
    return 0


def cmd_profile(cs, opts) -> int:
    """Request an on-demand deep capture: stamp the directive annotation;
    the reconcile admits it into status.profile and the heartbeat-ACK
    channel delivers it to process 0."""
    steps = max(1, opts.steps)
    directive = {"id": uuid.uuid4().hex[:12], "steps": steps}
    job = cs.tpujobs.get(opts.namespace, opts.name)
    annotations = (job.setdefault("metadata", {})
                      .setdefault("annotations", {}))
    annotations[PROFILE_ANNOTATION] = json.dumps(directive)
    cs.tpujobs.update(opts.namespace, job)
    print(f"profile {directive['id']} requested: {steps} raw step lap(s) "
          f"of {opts.namespace}/{opts.name} "
          f"(watch status.profile for Captured)")
    return 0


def cmd_top(cs, opts) -> int:
    fleet = _status_get(opts, "/api/fleet")
    gp = fleet.get("goodput") or {}
    pre = fleet.get("preemption") or {}
    st = fleet.get("stragglers") or {}
    print(f"Fleet: goodput {100 * float(gp.get('ratio') or 0):.1f}% "
          f"({gp.get('usefulStepSeconds', 0):.0f}s useful / "
          f"{gp.get('wallclockSeconds', 0):.0f}s wall), "
          f"{pre.get('restarts', 0)} restart(s) costing "
          f"{pre.get('lostStepSeconds', 0):.0f} lost step-seconds, "
          f"{st.get('flagged', 0)} straggler(s) / "
          f"{st.get('remediations', 0)} remediation(s)")
    for queue, q in sorted((fleet.get("queues") or {}).items()):
        print(f"Queue {queue!r}: wait p50 {_fmt_seconds(q.get('p50'))} "
              f"p95 {_fmt_seconds(q.get('p95'))} "
              f"over {q.get('count', 0)} admission(s)")
    rows = []
    for job in fleet.get("jobs") or []:
        ratio = job.get("goodputRatio")
        straggler = job.get("worstStragglerRatio")
        rows.append([
            f"{job.get('namespace', '')}/{job.get('name', '')}",
            job.get("phase", ""),
            job.get("queue") or "-",
            "-" if job.get("queuePosition") is None
            else str(job["queuePosition"]),
            "-" if ratio is None else f"{100 * float(ratio):.1f}%",
            "-" if not straggler else f"{float(straggler):.2f}x",
            "-" if job.get("lastDurableStep") is None
            else str(job["lastDurableStep"]),
            "-" if job.get("lastStep") is None else str(job["lastStep"]),
            str(job.get("restarts", 0)),
        ])
    _print_table(rows, TOP_COLUMNS)
    return 0


COMMANDS = {
    "submit": cmd_submit,
    "list": cmd_list,
    "get": cmd_get,
    "describe": cmd_describe,
    "delete": cmd_delete,
    "timeline": cmd_timeline,
    "profile": cmd_profile,
    "top": cmd_top,
}


def main(argv=None) -> int:
    parser = build_parser()
    opts = parser.parse_args(argv)
    if opts.version:
        print(version_mod.info())
        return 0
    if not opts.command:
        parser.print_help()
        return 2
    import yaml

    try:
        # Status-server commands need no apiserver client (and must not
        # demand a kubeconfig that may not exist on an observer's box).
        cs = (None if opts.command in STATUS_ONLY_COMMANDS
              else _clientset(opts))
        return COMMANDS[opts.command](cs, opts)
    except (errors.ApiError, OSError, yaml.YAMLError) as e:
        # OSError covers FileNotFoundError plus network-level failures
        # (connection refused, DNS, TLS) reaching the apiserver.
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
