"""ReplicaSnapshot: one read of the world per reconcile.

The reference reconciled by interrogating the apiserver per replica index —
``sync_services`` issued one GET per Service (replicas.go:538-568),
``SyncPods``/``GetStatus``/failure classification each issued one pod LIST
per index (replicas.go:481-535, 400-478) — so a single reconcile of an
N-worker job cost ~4·N synchronous read round trips, and the 256-1024
worker jobs the TPU redesign targets turned every reconcile into a read
storm. client-go's answer is the shared-informer lister: reads come from
the watch-maintained cache, writes are the only RPCs.

This module is the per-reconcile materialization of that idea: a
``ReplicaSnapshot`` is built ONCE per reconcile pass — from the informer
stores via the controlling-owner-UID index when the controller provides
them, or from a single label-selected pod LIST + service LIST when no
informer is attached (standalone TPUReplicaSet use in tests) — and every
classification (missing indices, per-replica state, retryable-failure
scan, service existence) is answered from it in memory.

Staleness contract: the snapshot can lag the apiserver by however far the
watch stream is behind. Consumers therefore treat it as *level-triggered
evidence*, never as proof of absence for write decisions with
non-idempotent effects: creates remain direct writes where a duplicate is
either impossible (deterministic Service names → benign 409 AlreadyExists)
or suppressed by the TrainingJob's in-flight create expectations; deletes
ignore 404s. Anything newly created shows up via its own watch event,
which enqueues the next reconcile.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from tpu_operator.apis.tpujob.v1alpha1.types import (
    LABEL_ATTEMPT,
    LABEL_JOB_TYPE,
    LABEL_TASK_INDEX,
)


def _labels(obj: Dict[str, Any]) -> Dict[str, str]:
    return (obj.get("metadata") or {}).get("labels") or {}


class ReplicaSnapshot:
    """Immutable-by-convention view of one job's pods and services, keyed
    the way the reconcile asks its questions: pods by (role, index),
    filtered by attempt on query; services by name.

    Objects inside MAY be shared with the informer cache — callers must not
    mutate them (the same discipline the raw Store imposes)."""

    def __init__(self, pods: List[Dict[str, Any]],
                 services: List[Dict[str, Any]]):
        self._pods = list(pods)
        self._by_role_index: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
        for pod in self._pods:
            lbls = _labels(pod)
            key = (lbls.get(LABEL_JOB_TYPE, ""), lbls.get(LABEL_TASK_INDEX, ""))
            self._by_role_index.setdefault(key, []).append(pod)
        self._services: Dict[str, Dict[str, Any]] = {
            (svc.get("metadata") or {}).get("name", ""): svc
            for svc in services
        }

    # -- construction ---------------------------------------------------------

    @classmethod
    def from_listers(cls, listers: Any, uid: str) -> "ReplicaSnapshot":
        """Zero-RPC build: pods/services of the controlling owner ``uid``
        straight from the informer stores' owner-UID index."""
        from tpu_operator.client.informer import INDEX_OWNER_UID

        return cls(listers.pods.by_index(INDEX_OWNER_UID, uid),
                   listers.services.by_index(INDEX_OWNER_UID, uid))

    @classmethod
    def from_clientset(cls, clientset: Any, namespace: str,
                       label_selector: str) -> "ReplicaSnapshot":
        """Fallback build when no informer is attached: exactly two reads
        (one pod LIST, one service LIST) regardless of replica count."""
        return cls(
            clientset.pods.list(namespace, label_selector=label_selector),
            clientset.services.list(namespace, label_selector=label_selector),
        )

    # -- queries --------------------------------------------------------------

    def pods_for(self, role: str, index: int,
                 attempt: Optional[int] = None) -> List[Dict[str, Any]]:
        """Pods of one replica index (all attempts, or one generation)."""
        pods = self._by_role_index.get((role.lower(), str(index)), [])
        if attempt is None:
            return list(pods)
        want = str(attempt)
        return [p for p in pods if _labels(p).get(LABEL_ATTEMPT) == want]

    def all_pods(self) -> List[Dict[str, Any]]:
        return list(self._pods)

    def pod_names(self) -> List[str]:
        return [(p.get("metadata") or {}).get("name", "") for p in self._pods]

    def has_service(self, name: str) -> bool:
        return name in self._services

    def service_names(self) -> List[str]:
        return list(self._services)

    def service(self, name: str) -> Optional[Dict[str, Any]]:
        return self._services.get(name)

    def __repr__(self) -> str:  # debugging/log aid
        return (f"ReplicaSnapshot(pods={len(self._pods)}, "
                f"services={len(self._services)})")
