"""TrainingJob: in-memory lifecycle of one TPUJob.

Reference parity: pkg/trainer/training.go:45-457 — the in-memory job object
the controller keeps per CRD UID, the status source of truth (training.go:56-59),
and the reconcile driver:

- ``setup``: defaulting + validation + accelerator injection + RuntimeId
  generation + phase transition None→Creating/Failed (training.go:229-285);
  skipped when the persisted phase shows setup already ran
  (training.go:220-223), which is what makes reconcile idempotent across
  operator restarts.
- ``setup_replicas`` (training.go:289-303).
- ``reconcile``: sync services/pods, roll up status, drive phase transitions,
  write CRD status back (training.go:346-441).
- ``get_status``: chief-based job state (training.go:132-168).
- ``cluster_spec``: role → ordered DNS name map (training.go:103-118).
- ``delete`` (training.go:305-323).

Phase machine (reference semantics at training.go:154-165,392-430, with the
TPU whole-group and time-aware additions):

    NONE ──setup──▶ CREATING ──chief running──▶ RUNNING
      │ invalid spec                │ chief succeeded ▶ DONE  (state Succeeded)
      ▼                            │ permanent failure ▶ FAILED
    FAILED                         │ retryable group failure / stall:
                                   │   within per-kind budget ▶ teardown,
                                   │     then BACKOFF ──release──▶ CREATING
                                   │     (instant when backoff base is 0)
                                   │   else ▶ FAILED (RetryBudgetExhausted)
    CLEANUP (explicit Delete) ──▶ DONE after children removed

Time-aware recovery (this file enforces; controller/deadlines.py wakes
reconciles at the exact obligation times):

- **stall watchdog** (``spec.stallTimeoutSeconds``): Running + no heartbeat
  and no phase change for the window → whole-group restart, reason
  ``StallDetected``, ledger kind ``stall``;
- **active deadline** (``spec.activeDeadlineSeconds``): wall time since the
  first entry into Creating exceeds it → terminal FAILED with reason
  ``DeadlineExceeded`` (suspension does not stop this clock — a parked job
  still ages toward its deadline, unlike batch/v1's startTime reset);
- **restart backoff** (``spec.restartBackoff``): teardown is immediate (the
  slice frees), the next gang-create parks in BACKOFF until
  ``status.backoffUntil``;
- **per-kind retry budgets**: the ``status.failures`` ledger classifies
  every restart (preemption/application/stall); application+stall restarts
  spend ``maxRestarts``, preemption restarts spend the larger
  ``maxRestarts * PREEMPTION_BUDGET_FACTOR`` — slice churn cannot exhaust
  the crash-loop budget;
- **TTL** (``spec.ttlSecondsAfterFinished``): a finished job is reaped
  (children then the TPUJob itself) once the TTL elapses.

Completed pods are retained so ``kubectl logs`` keeps working
(tf_job_design_doc.md:86); children are removed by Kubernetes GC through the
OwnerReferences when the TPUJob itself is deleted, or explicitly via
``delete()``.

TPU-native hardening baked in (SURVEY.md §7 "hard parts"):
- **gang pod creation**: each generation's pods are created all-or-none;
  on any failure the partial generation is rolled back so a TPU pod slice is
  never left stranded half-acquired (the reference's create-if-absent loop
  happily created partial jobs, replicas.go:509-525);
- **whole-group restart**: any retryable worker death tears down and
  recreates the entire generation under a bumped attempt label — a JAX
  process group cannot survive member loss, unlike MXNet's PS topology;
- **coordinator-first ordering**: services are created before pods, so the
  coordinator's DNS name resolves by the time any worker starts
  (the reference relied on MXNet client retry).
"""

from __future__ import annotations

import copy
import json
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpu_operator.apis.tpujob import helper, validation
from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults
from tpu_operator.apis.tpujob.v1alpha1.types import (
    ControllerConfig,
    DEFAULT_DRAIN_DEADLINE_SECONDS,
    DEFAULT_RESIZE_DEBOUNCE_SECONDS,
    DrainReason,
    DrainState,
    ELASTIC_REMEDIATION_CAP,
    FAILURE_LEDGER_CAP,
    FailureKind,
    FailureRecord,
    PREEMPTION_BUDGET_FACTOR,
    PROFILE_ANNOTATION,
    RestartPolicy,
    ReplicaState,
    State,
    StragglerPolicy,
    TPUJob,
    TPUJobPhase,
    TPUJobSpec,
)
from tpu_operator.client import errors
from tpu_operator.payload import profile as profile_mod
from tpu_operator.scheduler.inventory import job_demand, scheduling_params
from tpu_operator.trainer import elastic as elastic_mod
from tpu_operator.trainer import replicas as replicas_mod
from tpu_operator.trainer import serving as serving_mod
from tpu_operator.trainer.gang import EXPECTATION_TTL_SECONDS, GangRuntime
from tpu_operator.trainer.snapshot import ReplicaSnapshot
from tpu_operator.util.tracing import traced
from tpu_operator.util import lockdep
from tpu_operator.util.util import (
    format_rfc3339,
    now_rfc3339,
    parse_rfc3339,
    rand_string,
)

log = logging.getLogger(__name__)

# Patchable timestamp source for the phase timeline (tests freeze it).
_now = now_rfc3339

# Seconds of continuous healthy Running after which the consecutive-failure
# streak (the restart-backoff exponent) resets — the K8s Job controller's
# "pod ran long enough, forget the backoff" idiom.
BACKOFF_RESET_SECONDS = 300.0

# EXPECTATION_TTL_SECONDS now lives with the gang runtime (trainer/gang.py);
# re-exported above for existing importers.


def live_pod(pod: Dict[str, Any]) -> bool:
    """A pod still occupying hardware — anything not terminally finished
    (terminated pods are retained for logs long after their slice freed,
    so they must never count as held capacity)."""
    return (pod.get("status") or {}).get("phase") not in ("Succeeded",
                                                          "Failed")


class TrainingJob:
    """One reconciled TPUJob (ref: TrainingJob, training.go:45-86)."""

    def __init__(self, clientset: Any, recorder: Any, job: TPUJob,
                 config: Optional[ControllerConfig] = None,
                 metrics: Optional[Any] = None,
                 listers: Optional[Any] = None,
                 scheduler: Optional[Any] = None,
                 writeback: Optional[Any] = None):
        self.clientset = clientset
        self.recorder = recorder
        self.job = job
        self.config = config or ControllerConfig()
        self.metrics = metrics
        # Informer caches (client.informer.Listers). When present, every
        # steady-state read — child classification AND the status-writeback
        # diff — is served from cache; the apiserver sees only writes.
        self.listers = listers
        # Fleet scheduler (scheduler/fleet.FleetScheduler): the admission
        # gate consulted before any gang create, and the slice-accounting
        # ledger released on teardown/TTL/terminal failure. None (tests,
        # standalone use) = no admission control, the pre-fleet behavior.
        self.scheduler = scheduler
        # Global non-critical status-PUT token bucket
        # (scheduler/writeback.WritebackLimiter); None = every status
        # change writes immediately.
        self.writeback = writeback
        # True while a rate-limited status write is parked in memory; the
        # next_time_obligation arms a retry so it always lands.
        self._writeback_deferred = False
        # The mode-agnostic gang runtime (trainer/gang.py): replica sets,
        # the per-reconcile snapshot, create expectations, gang creation
        # with rollback, service sync (readiness-gated in serve mode),
        # per-generation teardown, and serve-mode replica trimming. This
        # object is what both train and serve reconciles drive; the
        # TrainingJob keeps the phase machine and policy.
        self.gang = GangRuntime(clientset, recorder, self, listers=listers)
        # True only while setup's spec mutations (defaults, runtimeId) await
        # persistence; status writebacks must not overwrite user spec edits.
        self._spec_dirty = False
        # True once the TTL reaper has deleted this job: the informer cache
        # may echo the object for a few more reconciles, and re-arming the
        # (already past) TTL obligation would hot-loop the reap path.
        self._reaped = False
        # The full object our own last status write returned: the freshest
        # base we know for the next write (the informer cache may lag it —
        # crucially including the spec persisted by setup's _spec_dirty
        # write, which a stale cached base would silently revert).
        self._last_applied: Optional[Dict[str, Any]] = None
        # Effective world view cache: (spec object, scale) -> scaled spec.
        # Invalidates whenever refresh() swaps the spec object or a new
        # attempt/scale changes the size (elastic grant or serving scale —
        # exclusive by validation, so one cache serves both).
        self._eff_cache: Optional[Tuple[Any, int, TPUJobSpec]] = None
        # Straggler-remediation handoff from the controller's heartbeat
        # thread to the (single-threaded per key) reconcile: one pending
        # (processId, policy, attempt) slot, latest wins.
        self._rem_lock = lockdep.lock("TrainingJob._rem_lock")
        self._pending_remediation: Optional[Tuple[int, str, int]] = None  # guarded-by: _rem_lock
        # Serving readiness handoff (controller heartbeat thread → the
        # reconcile's service gating): (attempt, frozenset of READY pids,
        # frozenset of KNOWN pids — replicas with any serving evidence,
        # ready or not; an index outside KNOWN keeps its Service, which
        # is what makes an operator restart routing-neutral — and the
        # epoch of the earliest beat expiry, the exact-time wakeup that
        # lets a wedged replica drop out of routing WITHOUT posting
        # anything; None = no live beats to expire).
        self._serving_ready: Optional[Tuple[int, frozenset, frozenset,
                                            Optional[float]]] = None  # guarded-by: _rem_lock
        # Maintenance-drain handoff (controller node-watch thread → the
        # reconcile): the cordoned node whose gang should cooperatively
        # drain, plus the attempt the cordon was observed against. One
        # slot, latest wins — a still-cordoned node re-detects on its
        # next node event.
        self._pending_maintenance: Optional[Tuple[str, int]] = None  # guarded-by: _rem_lock
        # Live-resize debounce: epoch at which scheduler headroom above
        # the granted size was FIRST observed in the current stretch; the
        # grow drain fires only once headroom has held for
        # resizeDebounceSeconds. In-memory on purpose — an operator
        # restart merely restarts the debounce window, it never loses a
        # grow (the headroom is re-observed on the next reconcile).
        self._grow_headroom_since: Optional[float] = None

    # -- gang-runtime passthrough (the pre-extraction public surface) ----------

    @property
    def replica_sets(self) -> List[replicas_mod.TPUReplicaSet]:
        return self.gang.replica_sets

    @replica_sets.setter
    def replica_sets(self, value: List[replicas_mod.TPUReplicaSet]) -> None:
        self.gang.replica_sets = value

    @property
    def _expected_pods(self) -> Dict[Tuple[str, int, int], Tuple[str, float]]:
        return self.gang.expected_pods

    @property
    def _avoid_nodes(self) -> Dict[Tuple[str, int], str]:
        return self.gang.avoid_nodes

    # -- phase transitions (observability: status.phaseTimeline) ---------------

    def _transition(self, phase: str) -> None:
        """Set the phase, stamping ``status.phaseTimeline`` on the *first*
        entry into each phase, and export the derived lifecycle durations
        (time-to-scheduled / time-to-running / total runtime) as histograms.
        Re-entries (group restart driving Running→Creating→Running) keep
        the original stamps, so durations always measure the first pass;
        ``status.lastTransitionTime`` complements this by stamping every
        phase *change* (the stall watchdog's fallback baseline)."""
        status = self.job.status
        if status.phase != phase:
            status.last_transition_time = _now()
        status.phase = phase
        if not phase:
            return
        timeline = status.phase_timeline
        if phase in timeline:
            return
        timeline[phase] = _now()
        if self.metrics is None:
            return
        stamp = parse_rfc3339(timeline[phase])
        creating = parse_rfc3339(timeline.get(TPUJobPhase.CREATING, ""))
        if stamp is None:
            return
        if phase == TPUJobPhase.CREATING:
            created = parse_rfc3339(
                self.job.metadata.get("creationTimestamp", ""))
            if created is not None:
                self.metrics.observe("job_time_to_scheduled_seconds",
                                     max(0.0, stamp - created))
        elif phase == TPUJobPhase.RUNNING and creating is not None:
            self.metrics.observe("job_time_to_running_seconds",
                                 max(0.0, stamp - creating))
        elif phase in (TPUJobPhase.DONE, TPUJobPhase.FAILED) \
                and creating is not None:
            self.metrics.observe("job_runtime_seconds",
                                 max(0.0, stamp - creating))

    # -- identity passthrough -------------------------------------------------

    @property
    def name(self) -> str:
        return self.job.name

    @property
    def namespace(self) -> str:
        return self.job.namespace

    @property
    def uid(self) -> str:
        return self.job.uid

    @property
    def metadata(self) -> Dict[str, Any]:
        return self.job.metadata

    @property
    def job_spec(self) -> TPUJobSpec:
        """The spec the CHILD-MANAGEMENT layer sees: for elastic jobs
        whose current attempt was granted fewer slices than spec'd, a
        scaled view (WORKER replicas and numSlices reflect the granted
        world, so pod counts, the process table, and the injected env —
        TPU_WORKER_HOSTNAMES / JAX_NUM_PROCESSES / MEGASCALE_* — all
        describe the gang that actually runs). The persisted spec
        (``self.job.spec``) is never mutated; scheduler demand and
        validation read it directly."""
        return self.effective_spec()

    def effective_spec(self) -> TPUJobSpec:
        spec = self.job.spec
        granted = elastic_mod.granted_slices(spec, self.job.status.elastic)
        scaler = elastic_mod.scaled_spec
        if granted is None:
            # Serving scale (mode: serve; exclusive with elastic by
            # validation): the recorded replica target reshapes the
            # WORKER set the same way an elastic grant reshapes a gang.
            granted = serving_mod.serving_replicas(spec,
                                                   self.job.status.serving)
            scaler = serving_mod.scaled_spec
        if granted is None:
            return spec
        cached = self._eff_cache
        if cached is not None and cached[0] is spec and cached[1] == granted:
            return cached[2]
        eff = scaler(spec, granted)
        self._eff_cache = (spec, granted, eff)
        return eff

    def refresh(self, job: TPUJob) -> None:
        """Adopt the latest cluster state of this job (same UID).

        The in-memory **status** stays the source of truth (ref:
        training.go:56-59): the informer cache can lag our own status
        writes, and adopting a stale status regresses the attempt counter —
        observed as a whole-group restart racing back to attempt 0 and
        re-creating the already-deleted generation. Spec is adopted from the
        cluster (users may edit it), but guarded against the same staleness:
        a cached object that predates setup has no runtimeId yet, and
        defaults are re-applied (idempotent) so derived fields like
        restartPolicy never silently revert.
        """
        if not job.spec.runtime_id and self.job.spec.runtime_id:
            job.spec.runtime_id = self.job.spec.runtime_id
        set_defaults(job.spec)
        if job.spec.to_dict() != self.job.spec.to_dict():
            self.replica_sets = []
        job.status = self.job.status
        self.job = job

    # -- setup (ref: training.go:216-303) -------------------------------------

    @traced
    def setup(self) -> None:
        """Defaults → validation → accelerators → runtime id → phase.

        Idempotent: a phase other than NONE means setup already ran on a
        previous operator incarnation; the persisted runtimeId keeps child
        names stable (ref: training.go:220-223, 272-274).
        """
        if self.job.status.phase != TPUJobPhase.NONE:
            return
        try:
            set_defaults(self.job.spec)
            validation.validate_tpujob_spec(self.job.spec)
            validation.validate_tpu_resources(self.job.spec)
            helper.configure_accelerators(self.job.spec, self.config)
        except validation.ValidationError as e:
            self._transition(TPUJobPhase.FAILED)
            self.job.status.state = State.FAILED
            self.job.status.reason = f"invalid job spec: {e}"
            if self.recorder:
                self.recorder.event(self, "Warning", "InvalidSpec", str(e))
            return
        if not self.job.spec.runtime_id:
            self.job.spec.runtime_id = rand_string(4)
        self._spec_dirty = True
        self._transition(TPUJobPhase.CREATING)
        self.job.status.state = State.RUNNING

    @traced
    def setup_replicas(self) -> None:
        """Build TPUReplicaSet instances once (ref: training.go:289-303)
        via the gang runtime — from the EFFECTIVE spec (elastic grant or
        serving scale), so every replica count downstream is the
        attempt's actual one; ``_sync_elastic``/``_sync_serving`` reset
        the cached sets when the world changes."""
        self.gang.setup_replicas()

    # -- cluster spec (ref: training.go:103-118) -------------------------------

    def cluster_spec(self) -> Dict[str, List[str]]:
        """role → ordered list of ``dns:port`` entries (the effective —
        elastic-granted — world)."""
        out: Dict[str, List[str]] = {}
        for role, _i, dns, port in replicas_mod.process_table(
            self.name, self.job_spec.runtime_id, self.job_spec
        ):
            out.setdefault(role.lower(), []).append(f"{dns}:{port}")
        return out

    # -- the per-reconcile read snapshot --------------------------------------

    def build_snapshot(self) -> ReplicaSnapshot:
        """One view of this job's children for the whole reconcile pass
        (gang runtime: informer indexes when attached — zero RPCs — else
        two label-selected LISTs)."""
        return self.gang.build_snapshot()

    # -- gang pod creation ----------------------------------------------------

    @traced
    def sync_pods_gang(self, attempt: int,
                       snapshot: Optional[ReplicaSnapshot] = None) -> None:
        """Create every missing pod of this generation, all-or-none with
        rollback, via the gang runtime (see GangRuntime.sync_pods_gang —
        the machinery is mode-agnostic; serve mode reuses it verbatim)."""
        self.gang.sync_pods_gang(attempt, snapshot)

    # -- status (ref: training.go:132-168) -------------------------------------

    def _chief_replica_set(self) -> Optional[replicas_mod.TPUReplicaSet]:
        tp = self.job.spec.termination_policy
        if tp is None:
            return None
        for rs in self.replica_sets:
            if rs.replica_type == tp.chief_replica_name:
                return rs
        return None

    @traced
    def get_status(self, snapshot: Optional[ReplicaSnapshot] = None) -> tuple:
        """(job_state, replica_statuses) — chief-based completion
        (ref: training.go:132-168): the chief replica's state decides
        Running/Succeeded/Failed. In WHOLE_GROUP mode any permanently-failed
        replica also fails the job (a JAX group without one worker computes
        nothing), which the reference's per-role independence never needed.
        All classification runs against one snapshot.
        """
        snap = snapshot or self.build_snapshot()
        attempt = self.job.status.attempt
        statuses = [rs.get_status(attempt, snap) for rs in self.replica_sets]

        state = State.RUNNING
        chief_rs = self._chief_replica_set()
        if chief_rs is not None:
            tp = self.job.spec.termination_policy
            chief_state = chief_rs.get_single_replica_status(
                tp.chief_replica_index, attempt, snap)
            if chief_state == ReplicaState.RUNNING:
                state = State.RUNNING
            elif chief_state == ReplicaState.SUCCEEDED:
                state = State.SUCCEEDED
            elif chief_state == ReplicaState.FAILED:
                state = State.FAILED

        if self.job.spec.restart_policy == RestartPolicy.WHOLE_GROUP:
            if any(s.state == ReplicaState.FAILED for s in statuses):
                state = State.FAILED
        return state, statuses

    # -- CRD status writeback (ref: training.go:326-343) -----------------------

    @traced
    def update_crd_status(self) -> None:
        """Write status to the apiserver only when it changed (the reference
        diffs get vs in-memory the same way to avoid hot-looping on its own
        updates, training.go:326-343) — but the diff base comes from memory,
        not a GET, so the steady-state no-change pass costs zero RPCs.

        The base is the object our OWN last write returned: we are the only
        status writer, so it is always at least as fresh as the informer
        cache AND — unlike the cache, which can lag our spec-persisting
        setup write within the very pass that made it — it is guaranteed to
        carry the spec we persisted (runtimeId, defaults). Basing a
        full-object PUT on a lagging cached copy would silently revert that
        spec while pods already carry its runtime_id in their names. Before
        this process's first write the cache (or one GET when no informer is
        attached) is the base. If a concurrent user edit made the base's
        resourceVersion stale, the PUT 409s and ONE fresh GET + retry
        resolves it — and re-bases us on the edited object."""
        base_src: Optional[Dict[str, Any]] = self._last_applied
        if base_src is None and self.listers is not None:
            base_src = self.listers.tpujobs.get(self.namespace, self.name)
        if base_src is None:
            try:
                base_src = self.clientset.tpujobs.get(self.namespace, self.name)
            except errors.ApiError as e:
                if errors.is_not_found(e):
                    return
                raise
        wire = self.job.status.to_dict()
        # Read-only compare against the shared base — the deepcopy below is
        # paid only when a write actually happens, never on the steady-state
        # no-change pass this PR benchmarks.
        if base_src.get("status") == wire and not self._spec_dirty:
            self._writeback_deferred = False
            return
        # Fleet-scale writeback batching: a NON-critical delta (telemetry,
        # replica roll-up, queue position — anything but a phase/attempt/
        # state transition or setup's spec persistence) defers when the
        # global token bucket is dry; the dirty status rides in memory and
        # lands coalesced into ONE PUT when the retry obligation fires.
        if (self.writeback is not None and not self._spec_dirty
                and not self._critical_status_delta(
                    base_src.get("status") or {}, wire)
                and not self.writeback.allow()):
            self._writeback_deferred = True
            return
        current = copy.deepcopy(base_src)

        def apply(base: Dict[str, Any]) -> Dict[str, Any]:
            base["status"] = wire
            if self._spec_dirty:
                # Persist setup's spec mutations (defaults, runtimeId)
                # exactly once; routine status writebacks must never carry
                # the in-memory spec, or a concurrent user spec edit gets
                # silently reverted.
                base["spec"] = self.job.spec.to_dict()
            return self.clientset.tpujobs.update(self.namespace, base)

        try:
            updated = apply(current)
        except errors.ApiError as e:
            if errors.is_not_found(e):
                return  # deleted underneath us; the GC path handles it
            if not errors.is_conflict(e):
                raise
            try:
                fresh = self.clientset.tpujobs.get(self.namespace, self.name)
            except errors.ApiError as e2:
                if errors.is_not_found(e2):
                    return
                raise
            updated = apply(fresh)
        # The server's response is the freshest full object we can know;
        # deep-copied so fake-clientset store aliases are never mutated.
        self._last_applied = copy.deepcopy(updated) if updated else current
        self._spec_dirty = False
        self._writeback_deferred = False

    # Status fields whose change makes a writeback CRITICAL (never
    # rate-limited): the restart/admission machinery reads these back, so
    # deferring them would defer correctness, not telemetry. ``startup``
    # is here because it is a ONE-SHOT: the payload drops its breakdown
    # after the statusserver's 200 ACK (PR 5 hardened exactly this field
    # past the heartbeat coalescing), so a deferred PUT that dies with
    # the operator would lose it forever — unlike the per-beat telemetry
    # the next heartbeat re-carries. ``stragglers`` is here because a
    # flag change is an eviction/replace SIGNAL the fleet scheduler and
    # operators act on — deferring it defers the action (stepTiming, by
    # contrast, is per-beat telemetry and rides the limiter).
    # ``elastic`` is here because the restart rebuild reads the GRANTED
    # size back from status to re-reserve what the gang actually holds —
    # a deferred sizing write that dies with the operator would
    # re-reserve the spec's full (phantom) size; it changes at most once
    # per attempt plus per remediation, so it cannot storm the limiter.
    # ``profile`` is here because the directive's delivery path POLLS
    # status (the heartbeat-ACK piggyback reads status.profile.state):
    # a Requested record parked behind the write limiter is a directive
    # the payload never sees until unrelated churn flushes it.
    # ``drain`` is here for the same delivery reason as ``profile`` —
    # the heartbeat-ACK piggyback polls status.drain.state — plus a
    # sharper failure mode: a Requested drain parked behind the limiter
    # never reaches the payload, and its deadline then hard-kills a gang
    # that was never actually asked to save.
    _CRITICAL_STATUS_FIELDS = ("phase", "attempt", "state", "reason",
                               "backoffUntil", "failures", "startup",
                               "stragglers", "elastic", "profile", "drain")

    def _critical_status_delta(self, base: Dict[str, Any],
                               wire: Dict[str, Any]) -> bool:
        return any(base.get(f) != wire.get(f)
                   for f in self._CRITICAL_STATUS_FIELDS)

    # -- reconcile (ref: training.go:346-441) ----------------------------------

    @traced
    def reconcile(self) -> None:
        """One idempotent reconcile pass."""
        phase = self.job.status.phase
        now = parse_rfc3339(_now())

        if phase == TPUJobPhase.NONE:
            self.setup()
            self.update_crd_status()
            phase = self.job.status.phase

        if phase in (TPUJobPhase.FAILED, TPUJobPhase.DONE):
            # TTL reaper (batch/v1 ttlSecondsAfterFinished): a finished job
            # past its TTL is deleted outright — children first, then the
            # TPUJob — so completed jobs don't accumulate forever.
            ttl_at = self._ttl_epoch()
            if ttl_at is not None and now is not None and now >= ttl_at:
                if not self._reaped:
                    self._reap_finished()
                return
            self.update_crd_status()
            return

        if phase == TPUJobPhase.CLEANUP:
            self.delete_resources()
            self._release_slices()
            self._transition(TPUJobPhase.DONE)
            self.update_crd_status()
            return

        # Active deadline: total wall time since the job first entered
        # Creating. Checked before any child sync so an expired job never
        # creates another generation (applies to Suspended/Backoff too —
        # parked time still ages toward the deadline).
        deadline_at = self._deadline_epoch()
        if deadline_at is not None and now is not None and now >= deadline_at:
            self.setup_replicas()
            self._record_failure(
                self.job.status.attempt, FailureKind.DEADLINE,
                f"activeDeadlineSeconds={self.job.spec.active_deadline_seconds} exceeded")
            if self.metrics is not None:
                self.metrics.inc("job_deadline_exceeded_total")
            self._fail(
                f"DeadlineExceeded: job active longer than "
                f"{self.job.spec.active_deadline_seconds}s",
                event_reason="DeadlineExceeded")
            self.update_crd_status()
            return

        self.setup_replicas()
        self._sync_profile()
        attempt = self.job.status.attempt

        # Cooperative-drain housekeeping: resolve directives stranded by a
        # raced restart, admit a pending maintenance drain from the node
        # watch, and enforce the per-directive deadline — a payload that
        # never ACKed or never exited falls back to the hard teardown the
        # drain was trying to soften. False = that teardown ended the pass.
        if not self._sync_drain(now, attempt):
            self.update_crd_status()
            return

        # Fleet-scheduler eviction directive, checked before the suspend/
        # backoff parking below: a victim sitting out a restart backoff has
        # no pods but still holds its reservation — the preemptor must get
        # the slices NOW, not when the backoff elapses. A gang that already
        # SUCCEEDED is not torn down: the pop released its reservation (the
        # preemptor has the capacity either way), and the normal roll-up
        # below lands Done instead of pointlessly re-running finished work.
        # A Running gang with a live heartbeat is evicted DRAIN-FIRST: the
        # directive stays pending (capacity still drains toward the
        # preemptor via the in-flight-eviction credit) while the payload
        # saves and exits planned; the hard pop happens at the planned
        # exit — or at the drain deadline.
        finished_despite_eviction = False
        if self.scheduler is not None and not self.job.spec.suspend:
            outcome = self._sync_eviction(attempt)
            if outcome == "handled":
                self.update_crd_status()
                return
            finished_despite_eviction = outcome == "finished"

        # Suspend/resume (spec.suspend, batch/v1 Job semantics): suspension
        # tears down the whole generation — a partial JAX group computes
        # nothing, so freeing part of the slice would waste the rest — and
        # parks the job in Suspended; clearing the flag re-gangs the SAME
        # attempt (no retry budget spent; checkpointed payloads resume).
        if self.job.spec.suspend:
            if phase != TPUJobPhase.SUSPENDED:
                # Delete only LIVE pods (like _fail): terminated pods keep
                # their logs and their verdict — a chief that already
                # exited 0 must still roll up to Done on resume, not
                # re-run.
                self._delete_live_pods()
                self._release_slices()
                self._transition(TPUJobPhase.SUSPENDED)
                self.job.status.state = State.UNKNOWN
                self.job.status.reason = "suspended by spec"
                # Pre-suspend replica roll-ups describe pods that no longer
                # run; a parked job showing "Running" replicas would lie.
                self.job.status.replica_statuses = []
                if self.recorder:
                    self.recorder.event(
                        self, "Normal", "JobSuspended",
                        f"deleted attempt {attempt}'s live pods; slice freed")
            self.update_crd_status()
            return
        if phase == TPUJobPhase.SUSPENDED:
            self._transition(TPUJobPhase.CREATING)
            self.job.status.state = State.RUNNING
            self.job.status.reason = ""
            # Resume forfeits any pending restart backoff: the user's
            # explicit action is a better signal than the crash-spacing
            # heuristic.
            self.job.status.backoff_until = ""
            if self.recorder:
                self.recorder.event(
                    self, "Normal", "JobResumed",
                    f"re-ganging attempt {attempt}")
            # fall through: the normal sync below recreates the gang.

        if phase == TPUJobPhase.BACKOFF:
            # The failed generation is already torn down; hold the next
            # gang-create until the release time (the controller's deadline
            # manager schedules a wakeup for that exact moment).
            release = parse_rfc3339(self.job.status.backoff_until)
            if release is not None and now is not None and now < release:
                self.update_crd_status()
                return
            self.job.status.backoff_until = ""
            self._transition(TPUJobPhase.CREATING)
            self.job.status.state = State.RUNNING
            if self.recorder:
                self.recorder.event(
                    self, "Normal", "BackoffComplete",
                    f"backoff elapsed; re-ganging attempt {attempt}")
            # fall through: the normal sync below creates the new gang.

        # Fleet-scheduler admission gate (scheduler/fleet.py): the whole
        # gang's slice demand must be admitted before any pod exists; an
        # unadmitted job parks in Queued before the snapshot — it does no
        # child I/O at all.
        if self.scheduler is not None and not finished_despite_eviction:
            if not self.scheduler.ensure_admitted(self._sched_key(),
                                                  uid=self.uid,
                                                  holds_hardware=self._holds_hardware,
                                                  **self._sched_args()):
                self._park_queued()
                self.update_crd_status()
                return
            if self.job.status.phase == TPUJobPhase.QUEUED:
                # Just admitted: leave the queue, enter the normal
                # gang-create path below under the current attempt.
                first_start = (TPUJobPhase.RUNNING
                               not in self.job.status.phase_timeline)
                self._transition(TPUJobPhase.CREATING)
                if first_start:
                    # Re-base the lifecycle origin to the ADMISSION: the
                    # Creating stamp from setup() predates the queue wait,
                    # and the deadline/runtime clocks must measure runtime
                    # budget, not how full the cluster was.
                    self.job.status.phase_timeline[TPUJobPhase.CREATING] = \
                        _now()
                self.job.status.state = State.RUNNING
                self.job.status.reason = ""
                self._sync_sched_status(queued=False)
                if self.recorder:
                    self.recorder.event(
                        self, "Normal", "Admitted",
                        f"slice capacity reserved; creating gang "
                        f"(attempt {attempt})")

        # Elastic sizing: the attempt's world size is granted from the
        # live inventory exactly once, at its gang-create boundary —
        # preferring maxSlices, shrinking instead of queueing, and
        # re-expanding when capacity returned. Must run before any child
        # I/O: the replica sets, env contract, and service set below all
        # describe the granted world.
        if not finished_despite_eviction and not self._sync_elastic():
            self.update_crd_status()
            return
        # Serving scale (mode: serve; exclusive with elastic): follow the
        # controller's traffic-derived desired replica count, renegotiating
        # the slice reservation through the scheduler — no attempt bump,
        # no gang restart; scale-down trims pods/services past the target.
        if not finished_despite_eviction and not self._sync_serving():
            self.update_crd_status()
            return
        self.setup_replicas()

        # ONE cache snapshot for the whole pass: every classification below
        # (service existence, missing indices, status roll-up, failure scan)
        # reads it instead of the apiserver — steady state is zero-read.
        snap = self.build_snapshot()

        # Straggler remediation (spec.elastic.stragglerPolicy): the
        # controller hands over a member that status.stragglers kept
        # flagging past the patience window. SHED is a whole-group
        # restart at one slice fewer (the teardown path returns);
        # REPLACE deletes the member's pod here — the delete's watch
        # event re-runs this reconcile, whose gang sync re-creates the
        # member into the same rendezvous slot, avoiding the old node.
        rem = self._take_remediation()
        if rem is not None:
            pid, policy, retry = rem
            if policy == StragglerPolicy.SHED:
                self._remediate_shed(attempt, pid)
                self.update_crd_status()
                return
            self._remediate_replace(attempt, pid, snap, retry=retry)

        # Services first: the coordinator's DNS name must resolve before any
        # worker calls jax.distributed.initialize (SURVEY.md hard part (c)).
        # Serve mode gates the per-replica Services on readiness — a
        # Service exists only while its replica's payload posts ``ready``
        # serving beats (created on the ready beat, deleted when readiness
        # is lost, restored on return); with NO serving evidence yet for
        # this generation (fresh job, or a freshly restarted operator
        # whose in-memory map is empty while the fleet serves) the
        # Service set is left untouched. Train mode keeps the
        # unconditional path byte-identical.
        self._sync_headless_service(snap)
        if serving_mod.is_serve(self.job.spec):
            gate = self._serving_gate()
            if gate is not None:
                ready, known = gate
                self.gang.sync_services(snap, ready_indices=ready,
                                        known_indices=known)
            # Level-triggered scale-down: pods the watch cache hadn't
            # echoed when the scale-down pass trimmed appear later (their
            # create events re-enqueue this job) and must still go — a
            # one-shot trim against a stale snapshot leaked them forever
            # (review finding). No-op at the current width.
            self.gang.trim_replicas(
                max(1, serving_mod.base_replicas(self.job_spec)), snap)
        else:
            self.gang.sync_services(snap)
        self.sync_pods_gang(attempt, snap)

        state, statuses = self.get_status(snap)
        self.job.status.replica_statuses = statuses

        if state == State.FAILED:
            self._fail("chief or group replica failed permanently")
        elif state == State.SUCCEEDED:
            self.job.status.state = State.SUCCEEDED
            self._transition(TPUJobPhase.DONE)
            self.job.status.reason = ""
            self._release_slices()
            if self.recorder:
                self.recorder.event(self, "Normal", "JobSucceeded",
                                    f"chief exited 0 on attempt {attempt}")
        else:
            # Whole-group restart check: retryable member death (classified
            # preemption vs application), or a stalled payload?
            failure: Optional[tuple] = None
            if self.job.spec.restart_policy == RestartPolicy.WHOLE_GROUP:
                # Precedence across replica sets mirrors the within-set
                # rule (replicas.retryable_failure_info): application >
                # planned > preemption. A crashing set must be billed to
                # the strict crash-loop budget even when another set's
                # collateral SIGKILL (or cooperative exit) is discovered
                # first — and a gang whose drain completed must be billed
                # planned even when a straggler process was SIGKILLed at
                # the deadline's edge.
                rank = {FailureKind.PREEMPTION: 0, FailureKind.PLANNED: 1}
                for rs in self.replica_sets:
                    info = rs.retryable_failure_info(attempt, snap)
                    if info is None:
                        continue
                    if (failure is None
                            or rank.get(info[0], 2) > rank.get(failure[0], 2)):
                        failure = info
                    if info[0] not in rank:
                        break
            stall_at = self._stall_epoch()
            if failure is not None:
                if failure[0] == FailureKind.PLANNED:
                    self._planned_restart(attempt, failure[1])
                else:
                    self._group_restart(attempt, failure[0], failure[1])
            elif stall_at is not None and now is not None and now >= stall_at:
                # Pods report Running but the payload made no observable
                # progress (no heartbeat, no phase change) for the whole
                # stall window: a hung collective holds the slice — same
                # teardown path as pod death.
                if self.metrics is not None:
                    self.metrics.inc("job_stalls_total")
                if self.recorder:
                    self.recorder.event(
                        self, "Warning", "StallDetected",
                        f"no heartbeat within "
                        f"{self.job.spec.stall_timeout_seconds}s; "
                        f"restarting whole group")
                self._group_restart(
                    attempt, FailureKind.STALL,
                    f"StallDetected: no heartbeat within "
                    f"{self.job.spec.stall_timeout_seconds}s")
            else:
                running = all(
                    s.state in (ReplicaState.RUNNING, ReplicaState.SUCCEEDED)
                    for s in statuses
                )
                self.job.status.state = State.RUNNING
                self._transition(
                    TPUJobPhase.RUNNING if running else TPUJobPhase.CREATING
                )
                if running:
                    # A recovered job must not keep reporting its last
                    # restart ("group restart: attempt N") forever — clear
                    # the reason once the group is healthy again.
                    self.job.status.reason = ""
                    # Sustained health decays the backoff exponent (the
                    # workqueue's forget() idiom): the streak resets once
                    # the group has been Running for the reset window, so
                    # an old crash burst stops inflating the delay applied
                    # to unrelated future failures.
                    if self.job.status.consecutive_failures and now is not None:
                        entered = parse_rfc3339(
                            self.job.status.last_transition_time)
                        if (entered is not None
                                and now - entered >= BACKOFF_RESET_SECONDS):
                            self.job.status.consecutive_failures = 0
                    # In-attempt live resize, the grow half: a healthy
                    # shrunk elastic gang drains and re-gangs wider once
                    # inventory headroom has held through the debounce —
                    # no failure required.
                    self._maybe_request_grow(now, attempt)

        self.update_crd_status()

    def _fail(self, reason: str, event_reason: str = "JobFailed") -> None:
        self.job.status.state = State.FAILED
        self._transition(TPUJobPhase.FAILED)
        self.job.status.reason = reason
        self.job.status.backoff_until = ""
        if self.recorder:
            self.recorder.event(self, "Warning", event_reason, reason)
        # Free the slice: surviving workers of a permanently-failed group sit
        # blocked in collectives holding TPU hardware forever. Delete the
        # still-live pods; terminated ones are kept so their logs survive
        # (tf_job_design_doc.md:86).
        self._delete_live_pods()
        self._release_slices()

    def _delete_live_pods(self) -> None:
        """Teardown path (gang runtime): delete LIVE pods off a fresh
        job-scoped LIST so no live pod survives on cache staleness."""
        self.gang.delete_live_pods()

    def _sync_profile(self) -> None:
        """Admit an on-demand deep-profile directive from the
        ``tpujobctl profile`` annotation into ``status.profile`` (state
        Requested). From there the status server piggybacks the directive
        on a heartbeat ACK to process 0, and the controller folds the
        capture result back to Captured. Idempotent per directive id:
        the annotation stays on the object, so re-admitting the same id
        must be a no-op — including after Captured, or the record would
        flap Requested forever."""
        raw = (self.job.metadata.get("annotations") or {}).get(
            PROFILE_ANNOTATION)
        if not raw:
            return
        try:
            directive = json.loads(raw)
        except (TypeError, ValueError):
            return
        if not isinstance(directive, dict):
            return
        rid = str(directive.get("id") or "")
        if not rid:
            return
        cur = self.job.status.profile or {}
        if cur.get("id") == rid:
            return
        try:
            steps = int(directive.get("steps")
                        or profile_mod.DEFAULT_STEPS)
        except (TypeError, ValueError):
            steps = profile_mod.DEFAULT_STEPS
        steps = max(1, min(profile_mod.MAX_STEPS, steps))
        self.job.status.profile = {
            "id": rid,
            "state": "Requested",
            "steps": steps,
            "time": _now(),
        }
        if self.recorder:
            self.recorder.event(
                self, "Normal", "ProfileRequested",
                f"profile {rid}: capture of {steps} raw step lap(s) "
                f"requested")

    # -- cooperative drain (planned restarts: resize / preemption /
    # maintenance) -------------------------------------------------------------

    def _drain_params(self) -> Tuple[int, int]:
        """(deadlineSeconds, resizeDebounceSeconds): ``spec.drain`` with
        the API defaults filling absent fields."""
        dr = self.job.spec.drain
        if dr is None:
            return (DEFAULT_DRAIN_DEADLINE_SECONDS,
                    DEFAULT_RESIZE_DEBOUNCE_SECONDS)
        return dr.deadline_seconds, dr.resize_debounce_seconds

    def _active_drain(self, attempt: int) -> Optional[Dict[str, Any]]:
        """The in-flight (Requested/Acked) directive addressed to the
        current attempt's gang, or None. A non-terminal record stamped
        for another attempt is NOT active: the gang it addressed is
        gone, and serving it to (or folding ACKs from) a successor
        would drain a gang nobody asked to drain."""
        cur = self.job.status.drain
        if (cur and cur.get("state") in (DrainState.REQUESTED,
                                         DrainState.ACKED)
                and int(cur.get("attempt", -1)) == int(attempt)):
            return cur
        return None

    def request_drain(self, reason: str, detail: str = "",
                      target_slices: Optional[int] = None) -> None:
        """Stamp a cooperative-drain directive into ``status.drain``
        (state Requested). From there the status server piggybacks it on
        a heartbeat ACK to process 0 (the profile-directive delivery
        path); the payload latches it, runs the gang-agreed verified
        save at the next step boundary, and every process exits
        EXIT_PLANNED — classified ``planned``, restarted with zero
        backoff off the preemption-factor budget. The deadline stamped
        here is the hard backstop: a payload that never ACKs or never
        exits is torn down the old way once it passes (``_sync_drain``).

        Idempotent while a directive for this attempt is in flight:
        call sites re-request level-triggered every reconcile, and a
        re-request must not reset the directive's identity or push its
        deadline out forever."""
        status = self.job.status
        attempt = status.attempt
        if self._active_drain(attempt) is not None:
            return
        deadline_s, _debounce = self._drain_params()
        new: Dict[str, Any] = {
            "id": rand_string(5),
            "state": DrainState.REQUESTED,
            "reason": reason,
            "attempt": int(attempt),
            "deadline": format_rfc3339(
                (parse_rfc3339(_now()) or 0.0) + deadline_s),
            "time": _now(),
        }
        if target_slices:
            new["targetSlices"] = int(target_slices)
        status.drain = new
        if self.recorder:
            extra = (f" toward {int(target_slices)} slice(s)"
                     if target_slices else "")
            self.recorder.event(
                self, "Normal", "DrainRequested",
                f"drain {new['id']} ({reason}){extra}: payload asked to "
                f"save and exit at a step boundary"
                + (f" — {detail}" if detail else "")
                + f"; hard teardown if not drained within {deadline_s}s")
        log.info("drain: %s attempt %d directive %s (%s)%s",
                 self._sched_key(), attempt, new["id"], reason,
                 f" target={target_slices}" if target_slices else "")

    def request_maintenance_drain(self, node: str, attempt: int) -> None:
        """Controller handoff (node-watch thread): a node hosting this
        job's gang pods was cordoned — ask the next reconcile to drain
        the gang so it saves and re-places around the node instead of
        dying uncheckpointed when the node empties. One slot, latest
        wins: a still-cordoned node re-detects on its next event."""
        with self._rem_lock:
            self._pending_maintenance = (str(node), int(attempt))

    def _take_maintenance(self, attempt: int) -> Optional[str]:
        with self._rem_lock:
            pending, self._pending_maintenance = \
                self._pending_maintenance, None
        if pending is None:
            return None
        node, hand_attempt = pending
        if hand_attempt != attempt \
                or self.job.status.phase not in (TPUJobPhase.RUNNING,
                                                 TPUJobPhase.CREATING):
            return None  # the gang the cordon was observed against is gone
        return node

    def _sync_drain(self, now: Optional[float], attempt: int) -> bool:
        """Drain-directive housekeeping, every reconcile:

        - a non-terminal directive stamped for an OLDER attempt lost a
          race with a real failure (the gang it addressed is gone) —
          resolve it Expired so it can never be served to, or ACKed by,
          the successor gang;
        - a suspension mid-drain expires the directive (the teardown it
          softened is happening anyway, on the user's explicit order);
        - admit a pending maintenance-drain handoff from the node watch;
        - enforce the deadline: a directive still in flight past it
          falls back to the hard teardown it was trying to soften —
          eviction pop + requeue for preemption drains, plain group
          restart (billed preemption: operator-initiated infra churn)
          otherwise. Returns False when that teardown ended the pass."""
        status = self.job.status
        cur = status.drain
        if (cur and cur.get("state") in (DrainState.REQUESTED,
                                         DrainState.ACKED)
                and int(cur.get("attempt", -1)) != int(attempt)):
            stale = dict(cur)
            stale["state"] = DrainState.EXPIRED
            status.drain = stale
        if self.job.spec.suspend:
            active = self._active_drain(attempt)
            if active is not None:
                gone = dict(active)
                gone["state"] = DrainState.EXPIRED
                status.drain = gone
            return True
        node = self._take_maintenance(attempt)
        if node is not None:
            self.request_drain(DrainReason.MAINTENANCE,
                               f"node {node} cordoned for maintenance")
        active = self._active_drain(attempt)
        if active is None:
            return True
        if status.phase not in (TPUJobPhase.RUNNING, TPUJobPhase.CREATING):
            # No gang to tear down (Queued/Backoff park the directive);
            # it resolves by attempt staleness or by the gang returning.
            return True
        deadline = parse_rfc3339(str(active.get("deadline", "")))
        if deadline is None or now is None or now < deadline:
            return True
        expired = dict(active)
        expired["state"] = DrainState.EXPIRED
        status.drain = expired
        reason = str(active.get("reason", ""))
        detail = (f"drain {active.get('id')} ({reason}) deadline expired "
                  f"without a planned exit; falling back to hard teardown")
        if self.recorder:
            self.recorder.event(self, "Warning", "DrainDeadlineExpired",
                                detail)
        if reason == DrainReason.PREEMPTION and self.scheduler is not None:
            evict = self.scheduler.pop_eviction(self._sched_key(),
                                                uid=self.uid)
            if evict is not None:
                self._preempt_to_queue(attempt, evict)
                return False
            # The eviction evaporated mid-drain (cancelled, or aimed at a
            # dead predecessor): restart in place, keeping the slot.
        self._group_restart(attempt, FailureKind.PREEMPTION, detail)
        return False

    def _sync_eviction(self, attempt: int) -> str:
        """Fleet-eviction delivery, drain-first. Returns:

        - ``"handled"`` — the gang was hard-preempted; the caller
          writes status and ends the pass;
        - ``"finished"`` — the gang already succeeded; the directive was
          consumed (releasing the reservation) and the caller's roll-up
          lands Done, skipping the admission gate;
        - ``"draining"`` — a cooperative drain is in flight for the
          eviction; the gang keeps running until its planned exit or
          the drain deadline;
        - ``"none"`` — no eviction pending."""
        peek = getattr(self.scheduler, "peek_eviction", None)
        if peek is not None:
            reason = peek(self._sched_key(), uid=self.uid)
        else:
            # Scheduler without a non-consuming peek (test doubles):
            # popping here preserves the pre-drain hard behavior.
            reason = self.scheduler.pop_eviction(self._sched_key(),
                                                 uid=self.uid)
        if reason is None:
            self._cancel_eviction_drain(attempt)
            return "none"
        state, _ = self.get_status(self.build_snapshot())
        if state == State.SUCCEEDED:
            if peek is not None:
                self.scheduler.pop_eviction(self._sched_key(), uid=self.uid)
            return "finished"
        if peek is None or not self._drain_worthwhile():
            if peek is not None:
                self.scheduler.pop_eviction(self._sched_key(), uid=self.uid)
            self._preempt_to_queue(attempt, reason)
            return "handled"
        self.request_drain(DrainReason.PREEMPTION, reason)
        return "draining"

    def _drain_worthwhile(self) -> bool:
        """Whether a cooperative drain can actually save anything. It
        needs a Running gang with a live heartbeat channel (the
        directive rides the heartbeat ACK — without one it would only
        sit out its deadline), and it is SKIPPED when the checkpoint
        store is already fresh: a victim whose last uploaded step equals
        its last reported step has nothing new to save, and draining it
        would only delay the preemptor by a directive round-trip."""
        status = self.job.status
        if status.phase != TPUJobPhase.RUNNING:
            return False
        hb = status.last_heartbeat or {}
        if not hb:
            return False
        store = status.store or {}
        uploaded = store.get("lastUploadedStep")
        step = hb.get("step")
        if (isinstance(uploaded, int) and isinstance(step, int)
                and uploaded >= step):
            return False
        return True

    def _cancel_eviction_drain(self, attempt: int) -> None:
        """The eviction that requested a preemption drain evaporated
        (the fleet's unjustified-eviction sweep cancelled it): withdraw
        a directive the payload has NOT yet adopted so the gang keeps
        running undisturbed. An ACKed directive is past withdrawal —
        the payload's latch is armed and the gang WILL exit planned;
        its classification then restarts in place (the eviction pop
        no-ops), the cheapest remaining outcome."""
        cur = self.job.status.drain or {}
        if (cur.get("reason") == DrainReason.PREEMPTION
                and cur.get("state") == DrainState.REQUESTED
                and int(cur.get("attempt", -1)) == int(attempt)):
            withdrawn = dict(cur)
            withdrawn["state"] = DrainState.EXPIRED
            self.job.status.drain = withdrawn
            if self.recorder:
                self.recorder.event(
                    self, "Normal", "DrainCancelled",
                    f"drain {cur.get('id')} withdrawn: the eviction that "
                    f"requested it was cancelled before the payload "
                    f"adopted it")

    def _planned_restart(self, attempt: int, detail: str) -> None:
        """Every process of the gang exited EXIT_PLANNED: the
        cooperative drain completed (gang-agreed verified save, orderly
        exit at a step boundary). Resolve the directive to Completed,
        export the drain latency and the per-reason planned-restart
        counter, then route by reason:

        - ``preemption``: consume the pending eviction and requeue (the
          drain-first eviction path) — the verified save just landed, so
          the preemptor takes the slices with ~zero lost step-seconds;
        - ``resize``/``maintenance`` (and a directive-less planned
          exit): restart in place — the attempt bump re-enters
          ``_sync_elastic``, which renegotiates toward maxSlices (the
          grow) or around capacity that left the inventory."""
        status = self.job.status
        cur = self._active_drain(attempt)
        reason = str(cur.get("reason", "")) if cur else ""
        if cur is not None:
            done = dict(cur)
            done["state"] = DrainState.COMPLETED
            if done.get("drainedStep") is None:
                # The payload's ACK carries the boundary step; a gang
                # that exited before its ACK posted falls back to the
                # freshest durable step we know.
                ck = status.checkpoint or {}
                hb = status.last_heartbeat or {}
                for source in (ck.get("lastCheckpointStep"),
                               hb.get("step")):
                    if isinstance(source, int):
                        done["drainedStep"] = source
                        break
            status.drain = done
            if self.metrics is not None:
                labels = {"namespace": self.namespace, "name": self.name}
                requested = parse_rfc3339(str(cur.get("time", "")))
                now_epoch = parse_rfc3339(_now())
                if requested is not None and now_epoch is not None:
                    self.metrics.observe(
                        "job_drain_seconds",
                        max(0.0, now_epoch - requested), labels=labels)
                self.metrics.inc(
                    "job_planned_restarts_total",
                    labels={**labels, "reason": reason})
        if reason == DrainReason.PREEMPTION and self.scheduler is not None:
            evict = self.scheduler.pop_eviction(self._sched_key(),
                                                uid=self.uid)
            if evict is not None:
                # Billed PLANNED (the drain did its job), but through the
                # eviction teardown: reservation released, job requeued.
                self._preempt_to_queue(
                    attempt,
                    f"{evict}; cooperative drain "
                    f"{cur.get('id') if cur else ''} completed",
                    kind=FailureKind.PLANNED)
                return
        self._group_restart(attempt, FailureKind.PLANNED, detail)

    def _maybe_request_grow(self, now: Optional[float],
                            attempt: int) -> None:
        """In-attempt live resize, the grow half: a Running elastic gang
        granted fewer slices than maxSlices drains and re-gangs wider
        WITHIN the job — no failure required — once the inventory has
        held enough free capacity for the full debounce window.
        Debounced because capacity free at the instant a neighbor
        restarts is routinely re-taken seconds later; thrashing a
        healthy gang for transient headroom costs more step-seconds
        than the width would earn back."""
        if now is None or self.scheduler is None:
            return
        rng = elastic_mod.elastic_range(self.job.spec)
        if rng is None:
            return
        _lo, hi = rng
        el = self.job.status.elastic or {}
        cur_slices = int(el.get("slices") or 0)
        if not cur_slices or cur_slices >= hi:
            self._grow_headroom_since = None
            return
        if self._active_drain(attempt) is not None:
            return
        headroom = getattr(self.scheduler, "grow_headroom", None)
        if headroom is None:
            return
        target = headroom(self._sched_key(), uid=self.uid, max_slices=hi)
        if target is None or target <= cur_slices:
            self._grow_headroom_since = None
            return
        _deadline, debounce = self._drain_params()
        if self._grow_headroom_since is None:
            self._grow_headroom_since = now
        if now - self._grow_headroom_since < debounce:
            return  # wakeup armed via next_time_obligation
        self._grow_headroom_since = None
        self.request_drain(
            DrainReason.RESIZE,
            f"inventory headroom for {int(target)}/{hi} slice(s) held "
            f"{debounce}s (running {cur_slices})",
            target_slices=int(target))

    def _record_failure(self, attempt: int, kind: str, reason: str) -> None:
        """Record one classified failure: an entry in the ``status.failures``
        ledger (bounded postmortem trail: oldest entries fall off past
        FAILURE_LEDGER_CAP), a tick of the per-kind lifetime counter the
        retry budgets charge (counters never decay — the bounded ledger
        must not silently re-arm an exhausted budget), and a tick of the
        consecutive-failure streak the backoff exponent uses.

        At most one record per failed attempt *and kind*: a group restart
        that dies mid-teardown (transient API error) is requeued and
        re-enters with the same attempt — double-recording would
        double-bill the retry budget. A different kind on the same attempt
        is a genuinely new failure (e.g. the deadline expiring after a
        retryable death, before the attempt bump persisted) and must still
        land in the ledger, or the postmortem trail would contradict the
        terminal reason."""
        status = self.job.status
        ledger = status.failures
        if any(f.attempt == attempt and f.kind == kind for f in ledger):
            return
        # The last durable step known right now is what the next attempt
        # resumes from — stamped into the record so the postmortem trail
        # (and `tpujobctl describe`) shows each restart's actual resume
        # point instead of leaving "did it go back to 0?" to guesswork.
        resume = None
        ck = status.checkpoint or {}
        hb = status.last_heartbeat or {}
        for source in (ck.get("lastCheckpointStep"),
                       hb.get("lastCheckpointStep")):
            if source is not None:
                try:
                    resume = int(source)
                except (TypeError, ValueError):
                    resume = None
                break
        # Elastic jobs: stamp the failed attempt's world size next to its
        # resume step, so a post-resize restart is auditable from the
        # ledger alone — which size ran, which step the next size
        # resumed from.
        world = None
        if self.job.spec.elastic is not None:
            el = status.elastic or {}
            if el.get("slices") and el.get("attempt") == attempt:
                world = int(el["slices"])
            else:
                world = max(1, self.job.spec.num_slices)
        # Progress the restart discards: the last step the attempt
        # reported minus the step it will resume from. Priced in
        # step-seconds by the fleet rollup — stamped HERE because only
        # the restart moment knows both numbers at once.
        lost = None
        gp = status.goodput or {}
        last_step = gp.get("lastStep", hb.get("step"))
        if resume is not None and last_step is not None:
            try:
                lost = max(0, int(last_step) - resume)
            except (TypeError, ValueError):
                lost = None
        ledger.append(FailureRecord(attempt=attempt, kind=kind,
                                    reason=reason, time=_now(),
                                    resume_step=resume,
                                    world_slices=world,
                                    lost_steps=lost))
        if len(ledger) > FAILURE_LEDGER_CAP:
            del ledger[:len(ledger) - FAILURE_LEDGER_CAP]
        status.restart_counts[kind] = status.restart_counts.get(kind, 0) + 1
        if kind != FailureKind.PLANNED:
            # Planned (cooperative-drain) exits are operator-initiated:
            # they must not inflate the crash-streak backoff exponent,
            # or a job that grew three times in a quiet hour would meet
            # its next real crash at 8x the base delay.
            status.consecutive_failures += 1

    def _group_restart(self, attempt: int, kind: str, reason: str) -> None:
        """Tear down the failed generation and start the next one
        (TPU-native; no reference equivalent — MXNet PS restarts per-pod).

        Time-aware: the failure is classified into the ledger first and the
        retry budget is **per kind** — application/stall restarts spend
        ``maxRestarts``, preemption restarts spend the larger
        ``maxRestarts * PREEMPTION_BUDGET_FACTOR`` — then teardown happens
        immediately (the slice frees) while the next gang-create is spaced
        by exponential backoff in phase Backoff."""
        if not self._teardown_generation(attempt, kind, reason):
            return  # budget exhausted; _fail already ran
        next_attempt = self.job.status.attempt
        self.job.status.state = State.RUNNING
        delay = 0.0
        backoff = self.job.spec.restart_backoff
        # Planned (cooperative-drain) restarts re-gang immediately: the
        # exit was orderly and the verified save landed — crash spacing
        # has nothing to space, and every backoff second is a scheduled
        # gang sitting idle on purpose.
        if backoff is not None and kind != FailureKind.PLANNED:
            # Exponent = consecutive failures since the last sustained
            # healthy stretch (this one included): restart 1 waits base,
            # restart 2 waits 2*base, ... capped. The streak resets after
            # BACKOFF_RESET_SECONDS of healthy Running, so a lone routine
            # preemption weeks after an early crash burst starts back at
            # the base delay instead of near the cap.
            delay = backoff.delay_for_restart(
                self.job.status.consecutive_failures)
        if delay > 0:
            release = (parse_rfc3339(_now()) or 0.0) + delay
            self.job.status.backoff_until = format_rfc3339(release)
            self._transition(TPUJobPhase.BACKOFF)
            self.job.status.reason = (
                f"group restart: attempt {next_attempt} in backoff for "
                f"{delay:.0f}s ({reason})")
            if self.metrics is not None:
                self.metrics.observe("group_restart_backoff_seconds", delay)
        else:
            self.job.status.backoff_until = ""
            self._transition(TPUJobPhase.CREATING)
            self.job.status.reason = (
                f"group restart: attempt {next_attempt} ({reason})")
        used, budget, _desc = self._restart_budget_usage(kind)
        if self.recorder:
            self.recorder.event(
                self, "Normal", "GroupRestart",
                f"{kind} failure ({reason}); restarting whole group "
                f"(attempt {next_attempt}; {used}/{budget} {kind} budget "
                f"used; backoff {delay:.0f}s)",
            )

    def _teardown_generation(self, attempt: int, kind: str,
                             reason: str) -> bool:
        """The shared restart teardown (group restart AND scheduler
        preemption): classify into the ledger, charge the per-kind
        budget, delete the generation's pods, drop its create
        expectations, and bump the attempt. False = budget exhausted
        (``_fail`` already ran and released the slices)."""
        self._record_failure(attempt, kind, reason)
        if not self._within_restart_budget(kind, reason):
            return False
        # Gang runtime: delete the generation's pods and drop its
        # in-flight create expectations + replace-remediation node
        # exclusions — the next gang places freely (and may be sized anew).
        self.gang.delete_pods_for_attempt(attempt)
        self.job.status.attempt = attempt + 1
        return True

    def _restart_budget_usage(self, kind: str) -> Tuple[int, int, str]:
        """(used, budget, description) of the per-kind retry budget:
        preemptions draw from ``maxRestarts * PREEMPTION_BUDGET_FACTOR``,
        application/stall restarts share ``maxRestarts``."""
        counts = self.job.status.restart_counts
        if kind in (FailureKind.PREEMPTION, FailureKind.PLANNED):
            # Planned (cooperative-drain) restarts are operator-initiated
            # slice churn, the same pool as preemptions: they share the
            # larger infra budget and can never exhaust the crash-loop
            # budget.
            used = (counts.get(FailureKind.PREEMPTION, 0)
                    + counts.get(FailureKind.PLANNED, 0))
            budget = self.job.spec.max_restarts * PREEMPTION_BUDGET_FACTOR
            return used, budget, f"{budget} preemption restarts"
        used = (counts.get(FailureKind.APPLICATION, 0)
                + counts.get(FailureKind.STALL, 0))
        budget = self.job.spec.max_restarts
        return used, budget, f"{budget} application restarts"

    def _within_restart_budget(self, kind: str, reason: str) -> bool:
        """Charge-check the (already-recorded) failure against its budget;
        on exhaustion the job fails terminally here and False returns."""
        used, budget, budget_desc = self._restart_budget_usage(kind)
        if used > budget:
            self._fail(
                f"retry budget exhausted: {used} {kind} failures exceed "
                f"{budget_desc} ({reason})"
            )
            return False
        return True

    # -- fleet scheduling (scheduler/fleet.py consults + accounting) -----------

    def _sched_key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def _sched_args(self) -> Dict[str, Any]:
        """The scheduler-facing view of this job: gang demand + the
        effective priority/queue (spec.scheduling, defaulted). Demand is
        derived from the ORIGINAL spec; elastic jobs additionally carry
        their sizing floor (``min_slices`` — admission may grant any
        size in [floor, demand]) and, for the rebuild force-admit path,
        the size the persisted ``status.elastic`` says the job actually
        holds (``held_slices`` — a shrunk gang must never re-reserve
        phantom spec-sized capacity after an operator restart)."""
        priority, queue = scheduling_params(self.job.spec)
        demand, kwargs = elastic_mod.sched_kwargs(
            self.job.spec, self.job.status.elastic,
            job_demand(self.job.spec))
        demand, serve_kwargs = serving_mod.sched_kwargs(
            self.job.spec, self.job.status.serving, demand)
        return {"demand": demand, "priority": priority, "queue": queue,
                **kwargs, **serve_kwargs}

    def _holds_hardware(self) -> bool:
        """Rebuild signal for the scheduler's restart path: this job's
        persisted state shows it already owns its slices (phase Running,
        or Creating with LIVE gang pods visible in the cache), so
        admission is an accounting fact to record, not a decision to
        make. Terminated pods do NOT count — they are retained for logs
        (tf_job_design_doc.md:86) long after the slice was released, and
        counting them force-admitted a resumed job past a full inventory
        on the strength of a finished pod."""
        phase = self.job.status.phase
        if phase == TPUJobPhase.RUNNING:
            return True
        if phase in (TPUJobPhase.CREATING, TPUJobPhase.BACKOFF):
            # BACKOFF holds its reservation across the gap by contract
            # (restarts must not lose their slot to the queue); live
            # pods-in-cache cover the Creating half.
            if phase == TPUJobPhase.BACKOFF:
                return True
            return any(live_pod(p)
                       for p in self.build_snapshot().all_pods())
        return False

    def _sync_sched_status(self, queued: bool) -> None:
        """Fold the scheduler view into ``status.scheduling``. Position
        updates are coarsened to MATERIAL changes (first sighting, the
        head of the queue, or a ≥20% move) so a 5k-deep queue draining
        does not write 5k² position-only PUTs."""
        args = self._sched_args()
        new: Dict[str, Any] = {"queue": args["queue"],
                               "priority": args["priority"]}
        if queued and self.scheduler is not None:
            pos = self.scheduler.queue_position(self._sched_key())
            if pos is not None:
                old = (self.job.status.scheduling or {}).get("position")
                material = (old is None or pos <= 2
                            or abs(pos - old) >= max(1, old // 5))
                new["position"] = pos if material else old
        self.job.status.scheduling = new

    def _park_queued(self) -> None:
        """No capacity for the whole gang: hold the job in phase Queued
        (no pods, slice untouched) until the admission queue promotes it."""
        status = self.job.status
        if status.phase != TPUJobPhase.QUEUED:
            self._transition(TPUJobPhase.QUEUED)
            status.state = State.UNKNOWN
            status.reason = "queued: waiting for slice capacity"
            status.backoff_until = ""
            # Pre-queue replica roll-ups describe pods that don't exist.
            status.replica_statuses = []
            if self.recorder:
                # ONE event per queueing decision (stable message, so the
                # recorder aggregates re-queues of the same job).
                self.recorder.event(
                    self, "Normal", "Queued",
                    "whole-gang slice demand does not fit the inventory; "
                    "waiting for capacity")
        # "Waiting" and "can never fit as specced" must not read the same:
        # a demand past the shape's total capacity says so in the reason.
        impossible = (self.scheduler.unschedulable_reason(self._sched_key())
                      if self.scheduler is not None else None)
        if impossible:
            status.reason = f"unschedulable: {impossible}"
        self._sync_sched_status(queued=True)

    def _preempt_to_queue(self, attempt: int, reason: str,
                          kind: str = FailureKind.PREEMPTION) -> None:
        """Scheduler eviction: tear the gang down as a PREEMPTION-kind
        restart (the PR-2 preemption budget — an eviction must requeue the
        job, not burn its crash-loop budget) and park it in Queued; the
        next admission re-gangs under a bumped attempt. The drain-first
        path passes kind=PLANNED: same teardown and requeue, but the
        ledger records that the gang saved and exited on request."""
        if self.metrics is not None:
            # Counted here — the actual eviction — not at pop_eviction: a
            # directive consumed by an already-succeeded gang is a no-op.
            self.metrics.inc("tpujob_preemptions_total")
        if not self._teardown_generation(attempt, kind, reason):
            return  # budget exhausted; _fail already ran + released
        self.job.status.backoff_until = ""
        self.job.status.replica_statuses = []
        if self.recorder:
            self.recorder.event(
                self, "Normal", "Preempted",
                f"{reason}; gang torn down, attempt "
                f"{self.job.status.attempt} re-queued")
        # Re-enter the admission queue right away so the job has a
        # position the moment the eviction lands. The re-offer can admit
        # IMMEDIATELY (the eviction freed more than the preemptor needed,
        # or another release raced in): then the job goes straight back to
        # Creating — parking it Queued-while-admitted would strand it,
        # since the scheduler's wakeup for this key already fired.
        readmitted = False
        if self.scheduler is not None:
            readmitted = self.scheduler.ensure_admitted(
                self._sched_key(), uid=self.uid, **self._sched_args())
        if readmitted:
            self._transition(TPUJobPhase.CREATING)
            self.job.status.state = State.RUNNING
            self.job.status.reason = f"preempted: {reason}; re-admitted"
            self._sync_sched_status(queued=False)
        else:
            self._transition(TPUJobPhase.QUEUED)
            self.job.status.state = State.UNKNOWN
            self.job.status.reason = f"preempted: {reason}"
            self._sync_sched_status(queued=True)

    def _release_slices(self) -> None:
        """Return this job's slice reservation (terminal phases, TTL reap,
        suspension, explicit delete). Idempotent."""
        if self.scheduler is not None:
            self.scheduler.release(self._sched_key())

    # -- elastic gangs (inventory-sized attempts + straggler remediation) ------

    def _sync_elastic(self) -> bool:
        """Size the current attempt's world from the live inventory
        (elastic jobs; rigid jobs no-op True). Runs exactly once per
        attempt — at its gang-create boundary, before any child I/O —
        and records the grant in ``status.elastic`` so env injection,
        pod counts, and the scheduler's accounting all agree on the
        gang that actually runs. Returns False when the shape cannot
        host even ``minSlices`` any more: the reservation was released
        and the job parked back in Queued."""
        spec = self.job.spec
        rng = elastic_mod.elastic_range(spec)
        if rng is None:
            return True
        status = self.job.status
        attempt = status.attempt
        cur = dict(status.elastic or {})
        if cur.get("attempt") == attempt and cur.get("slices"):
            return True  # this attempt is already sized
        lo, hi = rng
        target = elastic_mod.capped_max(cur, lo, hi)
        granted = target
        demand = job_demand(spec)
        if self.scheduler is not None and demand is not None:
            g = self.scheduler.resize(self._sched_key(), uid=self.uid,
                                      min_slices=lo, max_slices=target)
            if g is None:
                self._park_queued()
                return False
            granted = g
        new: Dict[str, Any] = {
            "slices": int(granted),
            "workers": elastic_mod.world_workers(spec, granted),
            "minSlices": lo,
            "maxSlices": hi,
            "attempt": attempt,
            "resizes": int(cur.get("resizes", 0)),
            "time": _now(),
        }
        # The shed cap is one-attempt: consumed by this sizing, never
        # copied forward — a later restart re-expands toward maxSlices
        # when capacity (and a healthy gang) allow.
        if cur.get("remediations"):
            new["remediations"] = cur["remediations"]
        prev = cur.get("slices")
        if prev and int(prev) != int(granted):
            direction = "down" if int(granted) < int(prev) else "up"
            new["resizes"] += 1
            new["lastResizeDirection"] = direction
            if self.metrics is not None:
                self.metrics.inc("job_elastic_resizes_total",
                                 labels={"direction": direction})
            if self.recorder:
                self.recorder.event(
                    self, "Normal", "ElasticResized",
                    f"attempt {attempt} ganged at {granted} slice(s), "
                    f"{direction} from {prev} (range {lo}-{hi})")
            log.info("elastic: %s attempt %d resized %s -> %s (%s)",
                     self._sched_key(), attempt, prev, granted, direction)
        elif cur.get("lastResizeDirection"):
            new["lastResizeDirection"] = cur["lastResizeDirection"]
        status.elastic = new
        if self.metrics is not None:
            self.metrics.set_gauge(
                "job_world_size", new["workers"],
                labels={"namespace": self.namespace, "name": self.name})
        if self.replica_sets and prev != granted:
            # The world changed: the cached replica sets (and with them
            # every pod count and env build) describe the old size.
            self.replica_sets = []
        return True

    # -- serving mode (readiness gating + traffic-driven scaling) --------------

    def _serving_gate(self) -> Optional[Tuple[set, set]]:
        """Serve-mode readiness gate for the per-replica Services:
        ``(ready_indices, known_indices)`` — a Service is created for a
        READY index and deleted only for a KNOWN-not-ready one; an index
        with NO evidence keeps whatever Service it has. That per-replica
        absence rule is what makes an operator restart routing-neutral:
        a fresh in-memory serving map (or one replica's first beat
        arriving before its peers') must never ungate the still-silent
        rest of a healthy fleet (review finding). None = no evidence for
        this generation at all — the reconcile skips gating entirely."""
        with self._rem_lock:
            handoff = self._serving_ready
        if handoff is None:
            return None
        attempt, ready, known, _expiry = handoff
        if attempt != self.job.status.attempt:
            return None  # evidence belongs to a previous generation
        return (serving_mod.ready_indices(self.job_spec, set(ready)),
                serving_mod.ready_indices(self.job_spec, set(known)))

    def update_serving_ready(self, attempt: int, ready_pids: set,
                             known_pids: Optional[set] = None,
                             next_expiry: Optional[float] = None) -> None:
        """Controller handoff (heartbeat thread OR the reconcile-time
        expiry sweep): the processes whose serving beats currently say
        ``ready``, every process with ANY serving evidence (stale
        included — a staled entry is known-not-ready, an absent one is
        unknown), and the epoch at which the earliest live beat goes
        stale — fed into ``next_time_obligation`` so the deadline
        manager wakes a reconcile exactly then and a wedged replica's
        Service is removed without it posting anything. One slot,
        latest wins."""
        with self._rem_lock:
            self._serving_ready = (
                int(attempt), frozenset(ready_pids),
                frozenset(known_pids if known_pids is not None
                          else ready_pids),
                next_expiry)

    def _serving_expiry_epoch(self) -> Optional[float]:
        """Epoch of the next serving-beat expiry (serve mode only)."""
        if not serving_mod.is_serve(self.job.spec):
            return None
        with self._rem_lock:
            handoff = self._serving_ready
        if handoff is None or handoff[0] != self.job.status.attempt:
            return None
        return handoff[3]

    def _sync_serving(self) -> bool:
        """Follow the controller's traffic-derived replica target
        (``status.serving.desiredReplicas``) — serve mode only; train
        mode no-ops True. Renegotiates the slice reservation through the
        fleet scheduler for slice-per-replica jobs (the elastic resize
        path — admission-queue arbitration, not a free grab), records the
        granted count in ``status.serving.replicas``, trims pods and
        Services past a scale-down target, and resets the cached replica
        sets so the next sync builds the new world. NO attempt bump and
        no restart anywhere: serve replicas are independent servers.
        Returns False only when even ``minReplicas`` no longer fits the
        inventory (the job parks in Queued, like an elastic floor miss)."""
        spec = self.job.spec
        if not serving_mod.is_serve(spec):
            return True
        status = self.job.status
        sv = dict(status.serving or {})
        lo, hi = serving_mod.replica_range(spec)
        base = max(1, serving_mod.base_replicas(spec))
        current = int(sv.get("replicas") or base)
        desired = int(sv.get("desiredReplicas") or current)
        desired = max(lo, min(hi, desired))
        if desired == current and sv.get("replicas"):
            return True
        granted = desired
        if (self.scheduler is not None
                and serving_mod.slice_per_replica(spec)
                and job_demand(spec) is not None):
            g = self.scheduler.resize(self._sched_key(), uid=self.uid,
                                      min_slices=min(lo, current),
                                      max_slices=desired)
            if g is None:
                self._park_queued()
                return False
            granted = int(g)
        sv["replicas"] = int(granted)
        status.serving = sv
        if granted != current:
            direction = "down" if granted < current else "up"
            # The recorded scale must land BEFORE the replica sets
            # rebuild: they are built from the effective (serving-scaled)
            # spec, and a trim against sets describing the OLD width
            # would leave the runtime asking a shrunken world for the
            # trimmed indices.
            self.gang.reset_replicas()
            self._eff_cache = None
            if direction == "down":
                # Independent servers: trimming is safe (and the point).
                self.gang.setup_replicas()
                self.gang.trim_replicas(granted, self.build_snapshot())
            if self.recorder:
                self.recorder.event(
                    self, "Normal", "ServingScaled",
                    f"serving replicas {current} -> {granted} "
                    f"(desired {desired} from traffic, range {lo}-{hi})")
            log.info("serving: %s scaled %d -> %d (desired %d)",
                     self._sched_key(), current, granted, desired)
        return True

    def excluded_node(self, replica_type: str, index: int) -> Optional[str]:
        """Node the replacement pod for (role, index) must avoid — set
        by a ``replace`` straggler remediation, consumed by
        TPUReplicaSet.pod_spec_with_index as a node anti-affinity."""
        return self._avoid_nodes.get((replica_type, index))

    def request_remediation(self, process_id: int, policy: str,
                            attempt: int,
                            retry: Optional[Callable[[], None]] = None
                            ) -> None:
        """Controller handoff (heartbeat thread): ask the next reconcile
        to execute ``policy`` on ``process_id``. One slot, latest wins —
        remediations are rare and a second flagged member is re-detected
        on the next beat. ``retry`` re-arms the remediation in the
        controller's tracker when execution fails transiently (the
        member re-qualifies on its next flagged beat instead of the
        policy silently doing nothing for the rest of the attempt)."""
        with self._rem_lock:
            self._pending_remediation = (int(process_id), policy,
                                         int(attempt), retry)

    def _take_remediation(self
                          ) -> Optional[Tuple[int, str,
                                              Optional[Callable[[], None]]]]:
        with self._rem_lock:
            pending, self._pending_remediation = \
                self._pending_remediation, None
        if pending is None:
            return None
        pid, policy, attempt, retry = pending
        if attempt != self.job.status.attempt \
                or self.job.status.phase not in (TPUJobPhase.RUNNING,
                                                 TPUJobPhase.CREATING):
            return None  # the flagged generation is already gone
        return pid, policy, retry

    def _record_remediation(self, attempt: int, pid: int, policy: str,
                            node: str = "") -> None:
        el = dict(self.job.status.elastic or {})
        trail = list(el.get("remediations") or [])
        entry: Dict[str, Any] = {"attempt": attempt, "processId": pid,
                                 "policy": policy, "time": _now()}
        if node:
            entry["node"] = node
        trail.append(entry)
        el["remediations"] = trail[-ELASTIC_REMEDIATION_CAP:]
        self.job.status.elastic = el
        if self.metrics is not None:
            self.metrics.inc("job_straggler_remediations_total",
                             labels={"policy": policy})

    def _remediate_replace(self, attempt: int, pid: int,
                           snapshot: ReplicaSnapshot,
                           retry: Optional[Callable[[], None]] = None
                           ) -> None:
        """Replace one persistently flagged member: delete its pod
        (recording the node so the replacement avoids it) and let the
        normal gang sync re-create the member into the SAME rendezvous
        slot — same process id, same coordinator, same attempt. No
        restart budget is spent: the gang never loses its slot, and the
        payload's own whole-group recovery (the surviving members see a
        member death and the operator re-gangs, or an elastic runtime
        re-admits the process) owns what happens inside the group. A
        TRANSIENT delete failure re-arms the remediation via ``retry``
        (the already-elapsed window re-fires on the next flagged beat)
        instead of the policy silently lapsing for the attempt."""
        table = replicas_mod.process_table(
            self.name, self.job_spec.runtime_id, self.job_spec)
        if pid < 0 or pid >= len(table):
            log.warning("remediation: process %d is outside the current "
                        "world (%d processes); skipping", pid, len(table))
            return
        role, index, _dns, _port = table[pid]
        pods = [p for p in snapshot.pods_for(role, index, attempt)
                if live_pod(p)]
        if not pods:
            return  # already gone (raced a restart/teardown)
        pod = max(pods, key=lambda p: (
            (p.get("metadata") or {}).get("creationTimestamp") or "",
            (p.get("metadata") or {}).get("name") or ""))
        name = (pod.get("metadata") or {}).get("name", "")
        node = (pod.get("spec") or {}).get("nodeName", "")
        try:
            self.clientset.pods.delete(self.namespace, name)
        except errors.ApiError as e:
            if not errors.is_not_found(e):
                log.warning("remediation: deleting straggler pod %s "
                            "failed (will retry on the next flagged "
                            "beat): %s", name, e)
                if retry is not None:
                    retry()
                return
        # Only a pod that actually died records its node exclusion — a
        # failed delete must not leave a stale anti-affinity behind for
        # an unrelated later re-create of this index.
        if node:
            self._avoid_nodes[(role, index)] = node
        self._expected_pods.pop((role.lower(), index, attempt), None)
        self._record_remediation(attempt, pid, StragglerPolicy.REPLACE,
                                 node)
        if self.recorder:
            self.recorder.event(
                self, "Normal", "StragglerReplaced",
                f"deleted pod {name} of process {pid} (persistently "
                f"flagged straggler); re-creating the member into the "
                f"same rendezvous"
                + (f", avoiding node {node}" if node else ""))
        log.info("remediation: replaced straggler process %d (pod %s, "
                 "node %s) of %s attempt %d", pid, name, node or "?",
                 self._sched_key(), attempt)

    def _remediate_shed(self, attempt: int, pid: int) -> None:
        """Shed one slice: whole-group restart at the current world size
        minus one slice, billed to the PREEMPTION budget (a slow host is
        an infrastructure problem, not an application crash — it must
        never exhaust the crash-loop budget). The cap applies to exactly
        the next attempt's sizing; later restarts re-expand freely."""
        el = dict(self.job.status.elastic or {})
        rng = elastic_mod.elastic_range(self.job.spec) or (1, 1)
        lo, _hi = rng
        current = int(el.get("slices") or self.job_spec.num_slices)
        target = current - 1
        if target < lo:
            # Already at the floor: there is no slice to shed. Fall back
            # to replacing the member instead of silently doing nothing.
            log.info("remediation: %s already at minSlices=%d; replacing "
                     "process %d instead of shedding", self._sched_key(),
                     lo, pid)
            self._remediate_replace(attempt, pid, self.build_snapshot())
            return
        self._record_remediation(attempt, pid, StragglerPolicy.SHED)
        el = dict(self.job.status.elastic or {})
        el["capNextAttempt"] = target
        self.job.status.elastic = el
        self._group_restart(
            attempt, FailureKind.PREEMPTION,
            f"StragglerShed: process {pid} persistently flagged; "
            f"re-ganging at {target} slice(s)")

    # -- time obligations (enforced here; woken exactly on time by
    # controller/deadlines.DeadlineManager) ------------------------------------

    def _start_epoch(self) -> Optional[float]:
        """When the job became active: first entry into Creating, falling
        back to the apiserver's creationTimestamp."""
        return (parse_rfc3339(
                    self.job.status.phase_timeline.get(TPUJobPhase.CREATING, ""))
                or parse_rfc3339(
                    self.job.metadata.get("creationTimestamp", "")))

    def _deadline_epoch(self) -> Optional[float]:
        """Epoch at which activeDeadlineSeconds expires (None: no deadline).

        A job parked in Queued that has NEVER run does not age toward the
        deadline: the clock measures runtime budget (batch/v1 counts from
        job start), and queue wait under a full inventory can legitimately
        exceed any sane deadline — failing a job 'DeadlineExceeded' that
        never created a pod would be nonsense. Once the job has run, queue
        time between preemption and re-admission DOES count, same as
        Suspended/Backoff parking (a preempted job must not dodge its
        deadline by waiting)."""
        ads = self.job.spec.active_deadline_seconds
        if not ads:
            return None
        if (self.job.status.phase == TPUJobPhase.QUEUED
                and TPUJobPhase.RUNNING
                not in self.job.status.phase_timeline):
            return None
        start = self._start_epoch()
        if start is None:
            return None
        return start + ads

    def _stall_epoch(self) -> Optional[float]:
        """Epoch at which the stall watchdog fires: the freshest sign of
        life (payload heartbeat, else the last phase change) plus
        stallTimeoutSeconds. Armed only while Running under WholeGroup —
        a stalled JAX group can only be recovered by group restart."""
        st = self.job.spec.stall_timeout_seconds
        if (not st
                or self.job.status.phase != TPUJobPhase.RUNNING
                or self.job.spec.restart_policy != RestartPolicy.WHOLE_GROUP):
            return None
        # hb["time"] is stamped by the OPERATOR at receipt
        # (statusserver.record_heartbeat), not by the payload — so a skewed
        # container clock cannot fake liveness or trigger false stalls.
        hb = self.job.status.last_heartbeat or {}
        candidates = [parse_rfc3339(str(hb.get("time", ""))),
                      parse_rfc3339(self.job.status.last_transition_time)]
        baseline = max((c for c in candidates if c is not None), default=None)
        if baseline is None:
            return None
        return baseline + st

    def _drain_deadline_epoch(self) -> Optional[float]:
        """Epoch of the active drain directive's hard-teardown deadline
        (None: no directive in flight for the current attempt)."""
        active = self._active_drain(self.job.status.attempt)
        if active is None:
            return None
        return parse_rfc3339(str(active.get("deadline", "")))

    def _grow_ready_epoch(self) -> Optional[float]:
        """Epoch at which observed grow headroom will have held for the
        full debounce window (armed only mid-debounce) — the wakeup
        that fires the resize drain of an otherwise-quiet healthy
        gang."""
        if self._grow_headroom_since is None:
            return None
        return self._grow_headroom_since + self._drain_params()[1]

    def _ttl_epoch(self) -> Optional[float]:
        """Epoch at which a finished job is reaped (None: keep forever)."""
        ttl = self.job.spec.ttl_seconds_after_finished
        if ttl is None:
            return None
        timeline = self.job.status.phase_timeline
        finished = (parse_rfc3339(timeline.get(TPUJobPhase.DONE, ""))
                    or parse_rfc3339(timeline.get(TPUJobPhase.FAILED, "")))
        if finished is None:
            return None
        return finished + ttl

    def next_time_obligation(self) -> Optional[float]:
        """Earliest future epoch at which this job needs a time-driven
        reconcile (backoff release, stall-watchdog expiry, active deadline,
        finished-TTL) — None when the job has no pending time obligation.
        The controller feeds this into its deadline manager after every
        reconcile, so enforcement is exact-time instead of waiting for the
        next resync."""
        if self._reaped:
            return None
        phase = self.job.status.phase
        candidates = []
        if phase in (TPUJobPhase.DONE, TPUJobPhase.FAILED):
            candidates.append(self._ttl_epoch())
        elif phase in (TPUJobPhase.CREATING, TPUJobPhase.RUNNING,
                       TPUJobPhase.BACKOFF, TPUJobPhase.SUSPENDED,
                       TPUJobPhase.QUEUED):
            if phase == TPUJobPhase.BACKOFF:
                candidates.append(
                    parse_rfc3339(self.job.status.backoff_until))
            candidates.append(self._stall_epoch())
            candidates.append(self._deadline_epoch())
            # Cooperative drain: the directive's hard-teardown deadline,
            # and the grow debounce maturing — both need an exact-time
            # reconcile even when the gang posts nothing.
            candidates.append(self._drain_deadline_epoch())
            candidates.append(self._grow_ready_epoch())
            # Serve mode: the earliest serving-beat expiry — the wakeup
            # that removes a wedged replica's Service on time even when
            # no event (beat, resync) would otherwise reconcile.
            candidates.append(self._serving_expiry_epoch())
            if self._expected_pods:
                # A pending create expectation is in-flight state: if the
                # created pod dies before ANY watch event shows it (so the
                # cache never learns it existed, and delete-repair has
                # nothing to repair), no event will ever requeue this job —
                # and the resync loop no longer re-dispatches unchanged
                # objects. Arm a wakeup just past the soonest expectation
                # expiry so the normal create-if-absent pass re-runs and
                # repairs the gang.
                now_epoch = parse_rfc3339(_now())
                if now_epoch is not None:
                    soonest = min(exp for _name, exp
                                  in self._expected_pods.values())
                    candidates.append(
                        now_epoch
                        + max(0.0, soonest - time.monotonic()) + 1.0)
        if self._writeback_deferred:
            # A rate-limited status write is parked in memory: arm a retry
            # just past the token bucket's refill so it always lands even
            # with no further events for this job.
            now_epoch = parse_rfc3339(_now())
            if now_epoch is not None:
                retry = 1.0
                if self.writeback is not None:
                    retry = max(0.1, self.writeback.retry_after())
                candidates.append(now_epoch + retry)
        live = [c for c in candidates if c is not None]
        return min(live) if live else None

    def _reap_finished(self) -> None:
        """TTL expiry: delete children, then the TPUJob itself (the K8s
        TTL-after-finished controller's behavior for batch Jobs)."""
        if self.recorder:
            self.recorder.event(
                self, "Normal", "TTLExpired",
                f"finished longer than "
                f"{self.job.spec.ttl_seconds_after_finished}s ago; "
                f"deleting job")
        self.delete_resources()
        self._release_slices()
        try:
            self.clientset.tpujobs.delete(self.namespace, self.name)
        except errors.ApiError as e:
            if not errors.is_not_found(e):
                raise
        self._reaped = True

    def _sync_headless_service(
            self, snapshot: Optional[ReplicaSnapshot] = None) -> None:
        self.gang.sync_headless_service(snapshot)

    # -- delete (ref: training.go:305-323) -------------------------------------

    @traced
    def delete_resources(self) -> None:
        """Delete children (gang runtime; ref: deleteResources via each
        replica set's Delete, training.go:423-430 → replicas.go:279-342)."""
        self.gang.delete_resources()

    @traced
    def delete(self) -> None:
        """Explicit teardown: phase → CLEANUP, remove children, → DONE
        (ref: training.go:305-323; K8s GC via OwnerReferences covers the
        CRD-deletion path without any operator action)."""
        self._transition(TPUJobPhase.CLEANUP)
        self.delete_resources()
        self._release_slices()
        self._transition(TPUJobPhase.DONE)
        self.update_crd_status()
