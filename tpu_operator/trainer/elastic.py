"""Elastic gang sizing + straggler-remediation bookkeeping.

The reference operator (and this reproduction through PR 9) treated a
gang's world size as immutable: a restart re-ganged exactly
``spec.numSlices`` replicas or parked in Queued — a shrunken slice pool
turned a recoverable preemption into indefinite queue wait, and the PR-9
straggler detector could *name* the member pacing the gang but do
nothing about it. This module holds the pure pieces of the
graceful-degradation layer (ROADMAP item 3):

- **Range derivation** (:func:`elastic_range`): the per-attempt sizing
  range ``[minSlices, maxSlices]`` from the spec, with the one-attempt
  shed cap (:func:`capped_max`) applied on top.
- **World scaling** (:func:`scaled_spec`): a spec whose WORKER replica
  count and ``numSlices`` reflect the attempt's GRANTED size — the
  object the child-management layer (pod creation, env injection,
  services, status roll-up) sees, so ``TPU_WORKER_HOSTNAMES`` /
  ``JAX_NUM_PROCESSES`` / ``MEGASCALE_*`` regenerate for the actual
  size with zero special-casing anywhere downstream. The persisted spec
  is never touched: scaling is a per-reconcile view.
- **Remediation pacing** (:class:`RemediationTracker`): when
  ``status.stragglers`` keeps flagging the same (attempt, process) for
  ``stragglerPatienceSeconds``, the tracker reports it DUE exactly once
  per attempt — the controller then asks the TrainingJob to execute
  ``spec.elastic.stragglerPolicy`` (replace / shed) on its next
  reconcile. A transient flag that clears before the window elapses
  resets the clock; a remediated process is never re-remediated within
  the same attempt (the replacement pod re-earns its own window).

The scheduler half (grant-in-range admission, reservation resize) lives
in scheduler/fleet.py; the checkpoint half (reshard-restore across mesh
sizes) in payload/checkpoint.py.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set, Tuple

from tpu_operator.apis.tpujob.v1alpha1.types import (
    StragglerPolicy,
    TPUJobSpec,
    TPUReplicaType,
)
from tpu_operator.util import joblife, lockdep


def elastic_range(spec: TPUJobSpec) -> Optional[Tuple[int, int]]:
    """The spec's sizing range ``(minSlices, maxSlices)``, or None for a
    rigid (non-elastic) job. Normalized defensively — replica sets can
    be built from cached objects that predate defaulting — but the
    shipped semantics come from defaults.py/validation.py."""
    el = spec.elastic
    if el is None:
        return None
    lo = max(1, int(el.min_slices))
    hi = int(el.max_slices) or max(1, spec.num_slices)
    return lo, max(lo, hi)


def capped_max(status_elastic: Optional[Dict[str, Any]],
               lo: int, hi: int) -> int:
    """The effective upper bound for the NEXT sizing: the spec's ``hi``
    unless a shed remediation left a one-attempt ``capNextAttempt`` in
    ``status.elastic`` (consumed by the sizing that honors it)."""
    cap = (status_elastic or {}).get("capNextAttempt")
    if not cap:
        return hi
    return max(lo, min(hi, int(cap)))


def granted_slices(spec: TPUJobSpec,
                   status_elastic: Optional[Dict[str, Any]]
                   ) -> Optional[int]:
    """The recorded grant that makes the attempt's world differ from the
    spec'd one, or None when the spec applies as written (non-elastic
    job, nothing recorded yet, or granted == numSlices)."""
    if spec.elastic is None or not status_elastic:
        return None
    g = status_elastic.get("slices")
    if not g:
        return None
    g = int(g)
    if g < 1 or g == max(1, spec.num_slices):
        return None
    return g


def scaled_spec(spec: TPUJobSpec, granted: int) -> TPUJobSpec:
    """A deep copy of ``spec`` whose world is ``granted`` slices: WORKER
    replica counts scale by ``granted / numSlices`` (validation
    guarantees divisibility) and ``numSlices`` becomes the grant — so
    the process table, env contract, services, and status roll-up all
    describe the attempt's ACTUAL gang. Non-WORKER compat roles
    (SCHEDULER/SERVER) never scale; elastic validation requires
    WholeGroup WORKER jobs anyway."""
    eff = TPUJobSpec.from_dict(spec.to_dict())
    base = max(1, spec.num_slices)
    for rs in eff.replica_specs:
        if rs.tpu_replica_type == TPUReplicaType.WORKER:
            rs.replicas = max(1, rs.replicas // base) * granted
    eff.num_slices = granted
    return eff


def world_workers(spec: TPUJobSpec, granted: int) -> int:
    """WORKER process count of a gang ganged at ``granted`` slices —
    the JAX world size (``job_world_size`` gauge)."""
    base = max(1, spec.num_slices)
    total = 0
    for rs in spec.replica_specs:
        if rs.tpu_replica_type == TPUReplicaType.WORKER:
            total += max(1, rs.replicas // base) * granted
    return total


def sched_kwargs(spec: TPUJobSpec,
                 status_elastic: Optional[Dict[str, Any]],
                 demand: Optional[Tuple[str, int]]
                 ) -> Tuple[Optional[Tuple[str, int]], Dict[str, Any]]:
    """(demand, extra ensure_admitted kwargs) for an elastic job: the
    demand becomes (key, effective max — shed cap applied) and the
    kwargs carry the sizing floor plus the size the persisted
    ``status.elastic`` says the job actually holds (the rebuild
    force-admit path re-reserves THAT, never the spec's phantom
    maximum). Rigid jobs pass through unchanged. The ONE home of this
    derivation — the live reconcile gate (TrainingJob._sched_args) and
    the controller's restart rebuild must never drift apart."""
    rng = elastic_range(spec)
    if rng is None or demand is None:
        return demand, {}
    lo, hi = rng
    el = status_elastic or {}
    hi = capped_max(el, lo, hi)
    key, _slices = demand
    held = el.get("slices")
    return (key, hi), {"min_slices": lo,
                       "held_slices": int(held) if held else hi}


def straggler_policy(spec: TPUJobSpec) -> Tuple[str, float]:
    """(policy, patienceSeconds) of the spec's remediation contract —
    ``("none", 0.0)`` when no elastic/serving block (or an explicit none)
    makes every flag informational only. Serve jobs carry theirs on
    ``spec.serving`` (validation restricts it to none/replace — the PR-9
    detector doubles as the tail-latency guard, and a persistently slow
    replica is replaced without touching the rest of the fleet)."""
    el = spec.elastic
    if el is not None and el.straggler_policy not in ("",
                                                     StragglerPolicy.NONE):
        return el.straggler_policy, float(el.straggler_patience_seconds)
    sv = spec.serving
    if sv is not None and sv.straggler_policy not in ("",
                                                      StragglerPolicy.NONE):
        return sv.straggler_policy, float(sv.straggler_patience_seconds)
    return StragglerPolicy.NONE, 0.0


class RemediationTracker:
    """Per-job persistence windows over straggler flags.

    ``observe`` is fed every straggler evaluation (the controller's
    cadence fold): it tracks how long each process has been
    CONTINUOUSLY flagged and returns the ones whose window just crossed
    ``patience`` — each at most once per attempt (the returned process
    is marked done immediately, so a pending-but-not-yet-executed
    remediation is never re-issued on the next beat). Thread-safe: the
    controller calls it under its jobs lock from heartbeat threads and
    forgets keys from reconcile workers.
    """

    def __init__(self) -> None:
        self._lock = lockdep.lock("RemediationTracker._lock")
        # key -> {"attempt": n, "since": {pid: first-flag epoch},
        #         "done": set(pid remediated this attempt)}
        self._jobs: Dict[str, Dict[str, Any]] = joblife.track(
            "RemediationTracker._jobs")  # per-job: forget; guarded-by: _lock

    def observe(self, key: str, attempt: int, flagged: Set[int],
                now: float, patience: float) -> List[int]:
        """Fold one evaluation; returns process ids due for remediation
        (flagged continuously >= ``patience`` and not yet remediated
        this attempt), ascending."""
        with self._lock:
            state = self._jobs.get(key)
            if state is None or state["attempt"] != attempt:
                # New attempt: the replaced/re-ganged processes start
                # fresh windows; old remediation marks are moot.
                state = {"attempt": attempt, "since": {}, "done": set()}
                self._jobs[key] = state
            since: Dict[int, float] = state["since"]
            for pid in list(since):
                if pid not in flagged:
                    del since[pid]  # flag cleared: the window resets
            due: List[int] = []
            for pid in sorted(flagged):
                t0 = since.setdefault(pid, now)
                if pid in state["done"]:
                    continue
                if now - t0 >= patience:
                    state["done"].add(pid)
                    due.append(pid)
            return due

    def retry(self, key: str, attempt: int, pid: int) -> None:
        """Un-mark a remediation that could NOT be executed (transient
        API error on the pod delete, member already gone): the process
        re-qualifies on its next flagged beat — its window is already
        elapsed, so the retry is immediate — instead of the policy
        silently doing nothing for the rest of the attempt."""
        with self._lock:
            state = self._jobs.get(key)
            if state is not None and state["attempt"] == attempt:
                state["done"].discard(pid)

    def forget(self, key: str) -> None:
        """Drop a deleted job's windows. Idempotent."""
        with self._lock:
            self._jobs.pop(key, None)
