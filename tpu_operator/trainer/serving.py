"""Serving-mode policy helpers (``spec.mode: serve``).

The pure half of the serving subsystem, mirroring ``trainer/elastic.py``
for elastic gangs: replica-count scaling math, the serving-scaled spec
view the child-management layer consumes, and the readiness bookkeeping
the controller hands the reconcile.

Scaling model: the controller aggregates every replica's serving
heartbeats (requests/sec, readiness, latency percentiles, loaded
snapshot step) into ``status.serving`` and computes a traffic-desired
replica count within ``spec.serving {minReplicas, maxReplicas,
targetRequestsPerSecondPerReplica}``; the TrainingJob's reconcile then
renegotiates its slice reservation through the fleet scheduler (exactly
the elastic ``resize`` path for slice-per-replica jobs) and runs the
gang runtime against a SERVING-SCALED spec view — WORKER replicas (and,
for slice-per-replica jobs, ``numSlices``) reflect the granted count.
No attempt bump and no gang restart anywhere in the path: serve
replicas are independent servers, so scaling is pod set arithmetic, not
a group lifecycle event.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Set, Tuple

from tpu_operator.apis.tpujob.v1alpha1.types import (
    DEFAULT_SERVE_TARGET_RPS,
    JobMode,
    ServingSpec,
    TPUJobSpec,
    TPUReplicaType,
)


def is_serve(spec: TPUJobSpec) -> bool:
    return spec.mode == JobMode.SERVE


def base_replicas(spec: TPUJobSpec) -> int:
    """The spec'd WORKER replica count — the scaling start point."""
    return sum(r.replicas for r in spec.replica_specs
               if r.tpu_replica_type == TPUReplicaType.WORKER)


def replica_range(spec: TPUJobSpec) -> Tuple[int, int]:
    """``(minReplicas, maxReplicas)`` — the spec'd replica count for both
    bounds when no serving block asks for scaling."""
    sv: Optional[ServingSpec] = spec.serving
    base = max(1, base_replicas(spec))
    if sv is None:
        return base, base
    lo = max(1, int(sv.min_replicas))
    hi = int(sv.max_replicas) or base
    return lo, max(lo, hi)


def target_rps(spec: TPUJobSpec) -> float:
    sv = spec.serving
    if sv is None:
        return DEFAULT_SERVE_TARGET_RPS
    return float(sv.target_requests_per_second_per_replica)


def desired_replicas(total_rps: float, spec: TPUJobSpec) -> int:
    """Traffic-derived replica target: enough replicas that each serves at
    most ``targetRequestsPerSecondPerReplica``, clamped to the range.
    Zero traffic floors at ``minReplicas`` — a serve job never scales to
    nothing (cold-start latency is the point of keeping it resident)."""
    lo, hi = replica_range(spec)
    per = target_rps(spec)
    if per <= 0:
        return lo
    want = int(math.ceil(max(0.0, float(total_rps)) / per))
    return max(lo, min(hi, want))


def serving_replicas(spec: TPUJobSpec,
                     status_serving: Optional[Dict[str, Any]]
                     ) -> Optional[int]:
    """The recorded serving scale that makes the current world differ
    from the spec'd one, or None when the spec applies as written."""
    if not is_serve(spec) or not status_serving:
        return None
    r = status_serving.get("replicas")
    if not r:
        return None
    r = int(r)
    if r < 1 or r == max(1, base_replicas(spec)):
        return None
    return r


def slice_per_replica(spec: TPUJobSpec) -> bool:
    """True when one serve replica is one whole slice — the configuration
    whose scaling renegotiates the fleet-scheduler reservation (replica
    delta == slice delta). ``numSlices == 1`` single-slice jobs scale
    pods without touching slice accounting."""
    return spec.num_slices > 1 and spec.num_slices == base_replicas(spec)


def scaled_spec(spec: TPUJobSpec, replicas: int) -> TPUJobSpec:
    """A deep copy of ``spec`` whose WORKER replica count is the serving
    scale; for slice-per-replica jobs ``numSlices`` follows, so slice
    demand and the scheduler's accounting stay one-slice-per-replica —
    EXACTLY the :func:`slice_per_replica` configuration whose scaling
    renegotiates the reservation (a ``numSlices == 1`` single-worker job
    must keep ``numSlices`` at 1: its scaling never touches slice
    accounting, and bumping the view would mint slice demand admission
    never granted). The persisted spec is never touched: scaling is a
    per-reconcile view (the elastic discipline)."""
    eff = TPUJobSpec.from_dict(spec.to_dict())
    for rs in eff.replica_specs:
        if rs.tpu_replica_type == TPUReplicaType.WORKER:
            rs.replicas = max(1, int(replicas))
    if slice_per_replica(spec):
        eff.num_slices = max(1, int(replicas))
    return eff


def sched_kwargs(spec: TPUJobSpec,
                 status_serving: Optional[Dict[str, Any]],
                 demand: Optional[Tuple[str, int]]
                 ) -> Tuple[Optional[Tuple[str, int]], Dict[str, Any]]:
    """(demand, extra ensure_admitted kwargs) for a serve job: once the
    traffic loop has scaled the replica count, the slice demand is the
    CURRENT scale — the live admission gate and the controller's restart
    rebuild must both re-reserve what the job actually runs, never the
    spec's original count (the elastic ``sched_kwargs`` discipline, one
    home for the derivation). Non-serve jobs pass through unchanged.

    Every serve job additionally tags its scheduler entry ``serve`` with
    its minimum slice footprint (``serve_min_slices``): victim selection
    ranks a serve fleet already at ``minReplicas`` as a WORSE preemption
    victim than a training gang — the fleet has no capacity left to give
    back without going dark, while a fresh-checkpoint training gang
    resumes where it left off. Slice-per-replica fleets above their
    floor rank normally (they can shrink back toward it first); fixed-
    footprint serve jobs are always at their floor."""
    if not is_serve(spec) or demand is None:
        return demand, {}
    key, slices = demand
    if not slice_per_replica(spec):
        return demand, {"serve": True, "serve_min_slices": slices}
    cur = int((status_serving or {}).get("replicas") or 0) or slices
    lo, _hi = replica_range(spec)
    return (key, cur), {"held_slices": cur, "serve": True,
                        "serve_min_slices": lo}


def ready_indices(spec: TPUJobSpec, ready_pids: Set[int]) -> Set[int]:
    """Map ready heartbeat process ids onto WORKER task indices. Serve
    jobs are WORKER-only by validation and the process table orders
    replica sets in spec order, so for the WORKER set the global process
    id IS the task index; non-WORKER compat roles never gate."""
    return {int(p) for p in ready_pids if int(p) >= 0}
