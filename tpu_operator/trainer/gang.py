"""GangRuntime: the mode-agnostic attempt lifecycle of one TPUJob's gang.

Extracted from ``trainer/training.py`` (which had grown the whole child-
management layer inline) so that BOTH job modes drive one runtime:

- **train** (the classic finite job): whole-gang all-or-none pod creation
  with rollback, coordinator-first service ordering, per-generation
  teardown under a bumped attempt;
- **serve** (``spec.mode: serve``): the same create/teardown machinery,
  plus readiness-gated per-replica Services (a Service routes only while
  its replica's payload posts ``ready`` serving beats) and replica
  trimming for traffic-driven scale-down — no attempt bump, because serve
  replicas are independent servers, not one JAX process group.

The runtime owns exactly the pieces that are about *children of one
generation* — replica sets, the per-reconcile read snapshot, client-go
style create expectations, gang creation/rollback, service sync, node
exclusions for straggler replacement, and deletion — while the
:class:`~tpu_operator.trainer.training.TrainingJob` keeps what is about
the *job*: the phase machine, failure classification and retry budgets,
scheduling/elastic/serving policy, and status writeback. This split is
also what unblocks live elastic resize (ROADMAP item 3): resizing is a
gang-runtime operation (trim/grow a generation) the policy layer can now
invoke without threading through the phase machine.

``owner`` is the policy-layer object (the TrainingJob): it provides
``name``/``namespace``/``uid``/``metadata``/``job_spec`` (the EFFECTIVE —
elastic- or serving-scaled — spec), ``config``, and ``excluded_node``,
exactly the surface :class:`~tpu_operator.trainer.replicas.TPUReplicaSet`
already consumes.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from tpu_operator.client import errors
from tpu_operator.trainer import labels as labels_mod
from tpu_operator.trainer import replicas as replicas_mod
from tpu_operator.trainer.snapshot import ReplicaSnapshot
from tpu_operator.util import lockdep
from tpu_operator.util.tracing import traced

log = logging.getLogger(__name__)

# Lifetime of an in-flight create expectation (client-go's
# ControllerExpectations TTL idiom): a pod we created but whose watch event
# hasn't reached the cache yet is expected — not re-created — for this long.
# Past the TTL the normal create-if-absent logic takes over again (covers
# the pathological created-then-deleted-before-ever-observed race).
EXPECTATION_TTL_SECONDS = 60.0


class GangRuntime:
    """Child management for one job's current generation (mode-agnostic)."""

    def __init__(self, clientset: Any, recorder: Any, owner: Any,
                 listers: Optional[Any] = None):
        self.clientset = clientset
        self.recorder = recorder
        self.owner = owner
        self.listers = listers
        self.replica_sets: List[replicas_mod.TPUReplicaSet] = []
        # In-flight pod-create expectations (client-go ControllerExpectations):
        # (role, index, attempt) -> (pod_name, monotonic expiry). Pod names
        # carry a random suffix, so a stale cache can't be allowed to trigger
        # a duplicate create the way 409s neutralize it for Services —
        # instead, a created-but-not-yet-observed pod suppresses re-creation
        # until the cache shows it (or the attempt moves on / TTL expires).
        self.expected_pods: Dict[Tuple[str, int, int], Tuple[str, float]] = {}
        # Nodes a replaced straggler's replacement must avoid, per
        # (role, index) of the CURRENT attempt (cleared on teardown —
        # the next generation re-places freely).
        self.avoid_nodes: Dict[Tuple[str, int], str] = {}

    # -- replica sets ----------------------------------------------------------

    def setup_replicas(self) -> None:
        """Build TPUReplicaSet instances once (ref: training.go:289-303)
        from the owner's EFFECTIVE spec (elastic grant / serving scale),
        so every replica count downstream is the generation's actual one;
        the policy layer calls :meth:`reset_replicas` when a new grant or
        scale changes the world."""
        if self.replica_sets:
            return
        for rs_spec in self.owner.job_spec.replica_specs:
            self.replica_sets.append(
                replicas_mod.TPUReplicaSet(self.clientset, self.recorder,
                                           self.owner, rs_spec))

    def reset_replicas(self) -> None:
        """Drop the cached replica sets (the world changed: new elastic
        grant, serving scale, or a spec edit)."""
        self.replica_sets = []

    # -- the per-reconcile read snapshot ---------------------------------------

    def build_snapshot(self) -> ReplicaSnapshot:
        """One view of this job's children for the whole reconcile pass:
        from the informer caches via the owner-UID index when the
        controller attached them (zero RPCs), else from exactly two
        label-selected LISTs."""
        if self.listers is not None:
            return ReplicaSnapshot.from_listers(self.listers,
                                                self.owner.uid)
        selector = labels_mod.to_selector(
            labels_mod.job_labels(self.owner.name,
                                  self.owner.job_spec.runtime_id))
        return ReplicaSnapshot.from_clientset(
            self.clientset, self.owner.namespace, selector)

    def prune_expectations(self, snapshot: ReplicaSnapshot,
                           attempt: int) -> None:
        """Drop create expectations that are observed (the cache now shows
        the pod), obsolete (older generation), or expired."""
        now = time.monotonic()
        observed = set(snapshot.pod_names())
        for key in list(self.expected_pods):
            name, expires = self.expected_pods[key]
            if key[2] != attempt or name in observed or now > expires:
                del self.expected_pods[key]

    def soonest_expectation(self) -> Optional[float]:
        """Monotonic expiry of the soonest pending create expectation, or
        None — the policy layer arms a wakeup just past it."""
        if not self.expected_pods:
            return None
        return min(exp for _name, exp in self.expected_pods.values())

    # -- gang pod creation -----------------------------------------------------

    @traced
    def sync_pods_gang(self, attempt: int,
                       snapshot: Optional[ReplicaSnapshot] = None) -> None:
        """Create every missing pod of this generation, all-or-none, fanned
        across the bounded create pool (``createParallelism``, default 16).

        If any creation fails, the pods created *in this call* are rolled
        back and the error propagates (→ rate-limited requeue). Without
        this, two jobs contending for one TPU pod slice each grab part of
        it and deadlock (SURVEY.md §7 hard part (a)). Serve mode reuses
        the path verbatim: the replica sets already describe the
        serving-scaled world, so "missing" is scale-aware for free.
        """
        snap = snapshot or self.build_snapshot()
        self.prune_expectations(snap, attempt)
        work: List[tuple] = []
        for rs in self.replica_sets:
            role = rs.replica_type.lower()
            for index in rs.missing_pod_indices(attempt, snap):
                if (role, index, attempt) in self.expected_pods:
                    continue  # created earlier; cache just hasn't shown it
                work.append((rs, role, index))
        if not work:
            return
        env_ctx = replicas_mod.EnvContext(
            self.owner.name, self.owner.job_spec.runtime_id,
            self.owner.job_spec)
        created: List[tuple] = []  # (role, index, pod_name)
        created_lock = lockdep.lock("gang.created_lock")

        def create_one(rs: replicas_mod.TPUReplicaSet, role: str,
                       index: int) -> None:
            pod = rs.create_pod_with_index(index, attempt, env_ctx=env_ctx,
                                           emit_event=False)
            with created_lock:
                created.append((role, index, pod["metadata"]["name"]))

        try:
            replicas_mod.run_creates(
                [lambda rs=rs, role=role, i=i: create_one(rs, role, i)
                 for rs, role, i in work],
                int(getattr(self.owner.config, "create_parallelism",
                            replicas_mod.DEFAULT_CREATE_PARALLELISM)),
            )
        except Exception:
            # Roll back on ANY failure — API rejection (quota, forbidden) or
            # a local pod-build error — never leave a partial generation
            # holding part of a slice.
            expires = time.monotonic() + EXPECTATION_TTL_SECONDS
            for role, index, pod_name in created:
                try:
                    self.clientset.pods.delete(self.owner.namespace,
                                               pod_name)
                except errors.ApiError as e:
                    if errors.is_not_found(e):
                        continue
                    # Delete failed: the pod is STILL LIVE, and the cache may
                    # not show it yet — an expectation must cover this index
                    # or the requeued pass would create a duplicate gang
                    # member for it off the stale snapshot.
                    log.warning("gang rollback: freeing pod %s failed: %s",
                                pod_name, e)
                    self.expected_pods[(role, index, attempt)] = (
                        pod_name, expires)
            if self.recorder:
                self.recorder.event(
                    self.owner, "Warning", "GangCreateFailed",
                    f"rolled back {len(created)} pods of attempt {attempt}",
                )
            raise
        expires = time.monotonic() + EXPECTATION_TTL_SECONDS
        for role, index, pod_name in created:
            self.expected_pods[(role, index, attempt)] = (pod_name, expires)
        if self.recorder and created:
            # ONE aggregated event per gang sync, not one per pod — at 256
            # workers the per-pod events were their own write storm.
            self.recorder.event(
                self.owner, "Normal", "SuccessfulCreate",
                f"Created {len(created)} pods (gang, attempt {attempt})",
            )

    # -- services --------------------------------------------------------------

    def sync_headless_service(
            self, snapshot: Optional[ReplicaSnapshot] = None) -> None:
        """The job-scoped headless Service (per-pod DNS backbone) — always
        present in both modes: serve replicas still need stable hostnames
        for the store watch and the operator's env contract; readiness
        gates only the per-replica ClusterIP routing."""
        svc = replicas_mod.headless_service_spec(self.owner)
        name = svc["metadata"]["name"]
        if snapshot is not None:
            exists = snapshot.has_service(name)
        else:
            try:
                self.clientset.services.get(self.owner.namespace, name)
                exists = True
            except errors.ApiError as e:
                if not errors.is_not_found(e):
                    raise
                exists = False
        if exists:
            return
        try:
            self.clientset.services.create(self.owner.namespace, svc)
        except errors.ApiError as e:
            # Stale snapshot double-create: deterministic name → benign.
            if not errors.is_already_exists(e):
                raise

    def sync_services(self, snapshot: ReplicaSnapshot,
                      ready_indices: Optional[Set[int]] = None,
                      known_indices: Optional[Set[int]] = None) -> None:
        """Per-replica Services, coordinator-first ordering preserved by
        the caller. ``ready_indices`` is the serve-mode readiness gate:
        when given, a WORKER index's Service is created while the index
        is ready and DELETED when it is KNOWN not-ready (reload in
        flight, explicit not-ready beat, expired beats) — an index
        absent from ``known_indices`` keeps its Service, so evidence
        gaps (operator restart, a peer's beat not yet arrived) never
        ungate a healthy fleet. ``None`` (train mode) keeps the
        unconditional create-if-absent path byte-identical to the
        pre-serving behavior."""
        for rs in self.replica_sets:
            if ready_indices is None:
                rs.sync_services(snapshot)
            else:
                rs.sync_services_gated(
                    snapshot, ready_indices,
                    known_indices if known_indices is not None
                    else ready_indices)

    # -- serve-mode scale-down -------------------------------------------------

    def trim_replicas(self, keep: int,
                      snapshot: Optional[ReplicaSnapshot] = None) -> int:
        """Serve-mode scale-down: delete WORKER pods (any attempt) and
        Services whose task index is ``>= keep``. Returns pods deleted.
        Safe for independent serve replicas only — the policy layer never
        calls this on a training gang (losing one member kills the JAX
        group)."""
        snap = snapshot or self.build_snapshot()
        deleted = 0
        for pod in snap.all_pods():
            md = pod.get("metadata") or {}
            lab = md.get("labels") or {}
            try:
                index = int(lab.get("task_index", -1))
            except (TypeError, ValueError):
                continue
            if index < keep:
                continue
            phase = (pod.get("status") or {}).get("phase")
            if phase in ("Succeeded", "Failed"):
                continue
            try:
                self.clientset.pods.delete(self.owner.namespace,
                                           md.get("name", ""))
                deleted += 1
            except errors.ApiError as e:
                if not errors.is_not_found(e):
                    log.warning("trim: deleting pod %s failed: %s",
                                md.get("name"), e)
        # Leftover per-index Services of the old (wider) world: the
        # snapshot already lists every service, so walk IT rather than
        # probing a guessed index range (a probe cap leaked services on
        # scale-downs wider than the cap). Kept: indices below the new
        # width plus the headless backbone; anything else matching this
        # job's per-index naming goes.
        keep_names = {rs.gen_name(index)
                      for rs in self.replica_sets
                      for index in range(keep)}
        keep_names.add(replicas_mod.headless_service_name(
            self.owner.name, self.owner.job_spec.runtime_id))
        prefixes = tuple(
            rs.gen_name(0).rsplit("-", 1)[0] + "-"
            for rs in self.replica_sets)
        for name in snap.service_names():
            if name in keep_names or not name.startswith(prefixes):
                continue
            try:
                self.clientset.services.delete(self.owner.namespace, name)
            except errors.ApiError as e:
                if not errors.is_not_found(e):
                    log.warning("trim: deleting service %s failed: %s",
                                name, e)
        # Trimmed indices' in-flight expectations are moot.
        for key in list(self.expected_pods):
            if key[1] >= keep:
                del self.expected_pods[key]
        return deleted

    # -- teardown --------------------------------------------------------------

    def delete_pods_for_attempt(self, attempt: int) -> None:
        """Whole-group restart support: delete one generation's pods, keep
        services (their selectors span attempts). Clears the generation's
        expectations and node exclusions — the next gang places freely."""
        for rs in self.replica_sets:
            rs.delete_pods_for_attempt(attempt)
        self.expected_pods.clear()
        self.avoid_nodes.clear()

    def delete_live_pods(self) -> None:
        """Teardown path: read LIVE state (one job-scoped LIST — not the
        snapshot, which may miss pods created moments ago) so no live pod
        survives on cache staleness. Rare by construction (fail/suspend),
        so the single read doesn't dent the zero-read steady state."""
        selector = labels_mod.to_selector(
            labels_mod.job_labels(self.owner.name,
                                  self.owner.job_spec.runtime_id))
        for pod in self.clientset.pods.list(self.owner.namespace,
                                            label_selector=selector):
            phase = (pod.get("status") or {}).get("phase", "")
            if phase in ("Succeeded", "Failed"):
                continue
            try:
                self.clientset.pods.delete(
                    self.owner.namespace, pod["metadata"]["name"]
                )
            except errors.ApiError as e:
                if not errors.is_not_found(e):
                    log.warning("freeing pod %s: %s",
                                pod["metadata"]["name"], e)
        # The pods above died by our own hand: their expectations must not
        # suppress the re-gang after a resume.
        self.expected_pods.clear()

    @traced
    def delete_resources(self) -> None:
        """Delete children (ref: deleteResources via each replica set's
        Delete, training.go:423-430 → replicas.go:279-342)."""
        self.setup_replicas()
        for rs in self.replica_sets:
            rs.delete()
        name = replicas_mod.headless_service_name(
            self.owner.name, self.owner.job_spec.runtime_id)
        try:
            self.clientset.services.delete(self.owner.namespace, name)
        except errors.ApiError as e:
            if not errors.is_not_found(e):
                log.warning("deleting headless service %s: %s", name, e)
