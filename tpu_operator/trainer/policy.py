"""Termination-state classification: the exit-code contract.

Reference parity: pkg/trainer/training.go:172-208
(``isRetryableTerminationState``) and README.md:107-121 — the user-facing
contract the whole restart machinery hangs off:

- exit code 0        → success
- exit codes 1-127   → permanent failure (job fails if the chief dies this way)
- exit codes 128-255 → retryable (typically signal deaths / preemption);
                       the replica is restarted
- OOMKilled          → NEVER retryable, regardless of exit code
                       (training.go:183-192: MXNet's SIGKILL exit code 137
                       would otherwise look retryable)

Kept in its own module (the reference buried it in training.go) because both
the replica classifier and the job-level status logic need it, and because it
is the most table-testable function in the system
(ref tests: training_test.go:31-87).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from tpu_operator.apis.tpujob.v1alpha1.types import FailureKind

# Pod-level failure reasons that carry no container exit code but are
# transient infrastructure events — on TPU these are routine (slice
# preemption, maintenance drains) and MUST trigger a group restart, not a
# permanent job failure. The reference never faced these: kubelet restarts
# MXNet pods in place, and GPU boxes aren't preempted the way TPU slices are.
RETRYABLE_POD_REASONS = frozenset(
    {"Evicted", "Preempted", "NodeLost", "Shutdown", "NodeShutdown",
     "UnexpectedAdmissionError", "DeadlineExceeded"}
)

# Exit codes produced by *external* termination signals: 137 (SIGKILL, when
# not OOM) and 143 (SIGTERM) are how node drains and slice preemptions look
# from inside the container — the graceful-shutdown signal and the
# follow-up kill. Classified preemption-kind so they draw from the larger
# preemption retry budget; every other retryable signal exit (SIGSEGV 139,
# SIGABRT 134, SIGBUS 135, ...) is the payload crashing — application-kind.
PREEMPTION_EXIT_CODES = frozenset({137, 143})

# Exit code produced by the payload itself when it completes a cooperative
# drain directive (operator-initiated: live resize, graceful preemption,
# node maintenance). Inside the retryable band so older operators still
# restart the gang, but classified **planned**-kind here: billed to the
# preemption-factor budget and never to the crash-loop backoff streak.
# Checked before PREEMPTION_EXIT_CODES — 160 is not a signal exit, so the
# two sets can never overlap, but the precedence makes the intent explicit.
PLANNED_EXIT_CODES = frozenset({160})


def classify_pod_failure(pod: Dict[str, Any], container_name: str = "tpu"
                         ) -> Optional[Tuple[str, str]]:
    """(FailureKind, reason detail) for a retryably-failed pod, None when
    the pod did not fail retryably.

    Kubelet-level failures (Evicted/Preempted/... with no container
    termination record) and external-signal exits (137 non-OOM, 143) are
    **preemption**-kind — routine TPU slice churn, billed to the larger
    preemption budget. A cooperative-drain completion (160) is
    **planned**-kind — same budget, never the backoff streak. Other
    retryable exits (128-255 band: SIGSEGV, SIGABRT, ...) are the payload
    dying — **application**-kind."""
    status = pod.get("status") or {}
    name = (pod.get("metadata") or {}).get("name", "")
    saw_container = False
    for cs in status.get("containerStatuses") or []:
        if cs.get("name") != container_name:
            continue
        term = (cs.get("state") or {}).get("terminated") or \
            (cs.get("lastState") or {}).get("terminated")
        if term:
            saw_container = True
            if is_retryable_termination_state(term):
                code = int(term.get("exitCode"))
                if code in PLANNED_EXIT_CODES:
                    kind = FailureKind.PLANNED
                elif code in PREEMPTION_EXIT_CODES:
                    kind = FailureKind.PREEMPTION
                else:
                    kind = FailureKind.APPLICATION
                return kind, f"pod {name} exited {code}"
    if saw_container:
        return None
    reason = status.get("reason", "")
    if status.get("phase") == "Failed" and reason in RETRYABLE_POD_REASONS:
        return FailureKind.PREEMPTION, f"pod {name} failed: {reason}"
    return None


def is_retryable_termination_state(terminated: Optional[Dict[str, Any]]) -> bool:
    """Given a containerStateTerminated dict, decide retryability
    (ref: training.go:172-208)."""
    if not terminated:
        return False
    if terminated.get("reason") == "OOMKilled":
        # ref: training.go:183-192 — OOM is never retryable
        return False
    exit_code = terminated.get("exitCode")
    if exit_code is None:
        return False
    return 128 <= int(exit_code) <= 255


def is_permanent_failure(terminated: Optional[Dict[str, Any]]) -> bool:
    """Non-zero, non-retryable termination (ref: training.go:172-208 inverse)."""
    if not terminated:
        return False
    exit_code = terminated.get("exitCode")
    if exit_code is None or int(exit_code) == 0:
        return False
    return not is_retryable_termination_state(terminated)


def is_success(terminated: Optional[Dict[str, Any]]) -> bool:
    if not terminated:
        return False
    return terminated.get("exitCode") == 0 and terminated.get("reason") != "OOMKilled"
