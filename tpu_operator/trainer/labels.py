"""Label book-keeping for child resources.

Reference parity: pkg/trainer/labels.go:23-33 (KubernetesLabels map +
ToSelector) and the label set stamped in replicas.go:120-129
(``fioravanzo.org=``, ``job_type``, ``runtime_id`` — plus ``task_index``
added per pod/service at replicas.go:135,175).

The reference's cleanup script selected on a stale ``kubeflow.org=`` key
(hack/scripts/cleanup_clusters.sh:5-7) — a quirk fixed here by exporting the
group key as a constant used everywhere.
"""

from __future__ import annotations

from typing import Dict

from tpu_operator.apis.tpujob.v1alpha1.types import (
    LABEL_ATTEMPT,
    LABEL_GROUP_KEY,
    LABEL_JOB_NAME,
    LABEL_JOB_TYPE,
    LABEL_RUNTIME_ID,
    LABEL_TASK_INDEX,
)
from tpu_operator.client.selectors import format_selector


def job_labels(job_name: str, runtime_id: str) -> Dict[str, str]:
    """Labels shared by every child of a job (group key carried bare,
    like the reference's ``fioravanzo.org=``)."""
    return {
        LABEL_GROUP_KEY: "",
        LABEL_JOB_NAME: job_name,
        LABEL_RUNTIME_ID: runtime_id,
    }


def replica_labels(job_name: str, runtime_id: str, replica_type: str) -> Dict[str, str]:
    """Labels for one replica set (ref: replicas.go:120-129)."""
    labels = job_labels(job_name, runtime_id)
    labels[LABEL_JOB_TYPE] = replica_type.lower()
    return labels


def index_labels(job_name: str, runtime_id: str, replica_type: str, index: int,
                 attempt: int = 0) -> Dict[str, str]:
    """Labels for one replica index (ref: replicas.go:135,175 add task_index).
    ``attempt`` tags the whole-group restart generation (TPU-native)."""
    labels = replica_labels(job_name, runtime_id, replica_type)
    labels[LABEL_TASK_INDEX] = str(index)
    labels[LABEL_ATTEMPT] = str(attempt)
    return labels


def to_selector(labels: Dict[str, str]) -> str:
    """ref: labels.go:28-33."""
    return format_selector(labels)
