"""TPUReplicaSet: per-role reconciliation of pods and discovery services.

Reference parity: pkg/trainer/replicas.go:45-588 (MXReplicaSet) — one
instance per replicaSpec, responsible for:

- DNS-safe child naming ``{job}-{role}-{runtimeid}-{index}``
  (replicas.go:570-577), pods with an extra random suffix
  (replicas.go:579-583);
- one ClusterIP Service per replica index, selector = labels + task_index
  (replicas.go:132-159);
- pod creation from the user PodTemplateSpec with schedulerName passthrough
  and env injection into the magic container (replicas.go:162-276);
- create-if-absent sync loops (replicas.go:481-535, 538-568);
- deletion by label selector (replicas.go:279-342);
- pod-list → replica-state classification (replicas.go:345-398) and status
  roll-up (replicas.go:400-478).

The TPU-native redesign replaces the MXNet ``DMLC_*`` parameter-server env
contract (replicas.go:235-260) with the JAX/XLA process-group contract: every
replica receives ``JAX_COORDINATOR_ADDRESS``/``JAX_PROCESS_ID``/
``JAX_NUM_PROCESSES`` plus ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES`` (and
``MEGASCALE_*`` DCN-discovery vars for multi-slice jobs), so
``jax.distributed.initialize()`` inside the container forms one process group
over the slice. Collective bytes ride TPU ICI — the operator's surface stays
bootstrap-only, exactly like the reference.

Reference quirks deliberately fixed (SURVEY.md "quirks to fix, not copy"):
- coordinator address derives from the SCHEDULER *role* (or WORKER[0] in
  scheduler-less mode), not blindly ``Replicas[0]``  (bug at replicas.go:240-243);
- an empty pod list classifies as STARTING, not Running (bug at replicas.go:358-360);
- per-replica status queries go through the label selector that actually
  matches (the reference's Get-by-name at replicas.go:402 could never hit,
  because pods carry a random suffix, replicas.go:579-583);
- ``delete`` issues one pod DeleteCollection, not two (copy-paste bug at
  replicas.go:292-302);
- no stray debug prints (replicas.go:208-210,506).
"""

from __future__ import annotations

import copy
import logging
from concurrent.futures import FIRST_EXCEPTION, ThreadPoolExecutor, wait
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpu_operator.apis.tpujob import helper
from tpu_operator.apis.tpujob.v1alpha1.types import (
    DEFAULT_CONTAINER_NAME,
    DEFAULT_SERVE_RELOAD_POLL,
    DEFAULT_TPU_PORT,
    CacheMedium,
    FailureKind,
    JobMode,
    RestartPolicy,
    ReplicaState,
    TPUJobSpec,
    TPUReplicaSpec,
    TPUReplicaStatus,
    TPUReplicaType,
)
from tpu_operator.client import errors
from tpu_operator.trainer import labels as labels_mod
from tpu_operator.trainer import policy
from tpu_operator.trainer.snapshot import ReplicaSnapshot
from tpu_operator.util.tracing import traced
from tpu_operator.util.util import rand_string

log = logging.getLogger(__name__)

# Service port name (the reference left its port unnamed; naming it makes
# multi-port templates unambiguous).
PORT_NAME = "tpujob-port"

_MAX_DNS_LABEL = 63

# Bound on concurrent child-create RPCs per sync (--create-parallelism):
# a 256-pod gang costs ~N/16 round trips instead of N sequential ones.
DEFAULT_CREATE_PARALLELISM = 16

# Volume name of the persistent XLA compilation cache mount
# (spec.compilationCache); a user template already defining it wins.
CACHE_VOLUME_NAME = "tpujob-compilation-cache"


def run_creates(tasks: List[Callable[[], Any]], parallelism: int) -> None:
    """Run create thunks across a bounded worker pool with first-error
    propagation: on the first failure, queued tasks are cancelled, in-flight
    ones are allowed to finish (their effects are visible to the caller's
    rollback), and the first exception is re-raised. ``parallelism <= 1``
    degrades to the plain sequential loop."""
    if not tasks:
        return
    if parallelism <= 1 or len(tasks) == 1:
        for task in tasks:
            task()
        return
    with ThreadPoolExecutor(max_workers=min(parallelism, len(tasks)),
                            thread_name_prefix="gang-create") as pool:
        futures = [pool.submit(t) for t in tasks]
        done, not_done = wait(futures, return_when=FIRST_EXCEPTION)
        first_error: Optional[BaseException] = None
        for f in done:
            err = f.exception()
            if err is not None:
                first_error = err
                break
        if first_error is None:
            return
        for f in not_done:
            f.cancel()
        # pool.__exit__ joins the still-running tasks; cancelled ones never
        # start, so nothing is created behind the caller's back after this.
    raise first_error


# --- Naming (ref: replicas.go:570-583) --------------------------------------

def gen_general_name(job_name: str, replica_type: str, runtime_id: str, index: int) -> str:
    """Stable child name ``{job}-{role}-{runtimeid}-{index}``
    (ref: replicas.go:570-577), truncated from the front of the job name if
    needed to stay a valid DNS-1035 label."""
    suffix = f"-{replica_type.lower()}-{runtime_id}-{index}"
    room = _MAX_DNS_LABEL - len(suffix)
    return f"{job_name[:room]}{suffix}"


def gen_pod_name(job_name: str, replica_type: str, runtime_id: str, index: int) -> str:
    """Pod name = general name + random suffix so a replacement pod never
    collides with a terminating one (ref: replicas.go:579-583)."""
    base = gen_general_name(job_name, replica_type, runtime_id, index)
    suffix = f"-{rand_string(5)}"
    return f"{base[: _MAX_DNS_LABEL - len(suffix)]}{suffix}"


def headless_service_name(job_name: str, runtime_id: str) -> str:
    """Job-scoped headless Service for worker enumeration (TPU-native; the
    megascale/DCN analogue of the reference's per-replica Services)."""
    suffix = f"-{runtime_id}"
    return f"{job_name[: _MAX_DNS_LABEL - len(suffix)]}{suffix}"


# --- Cluster topology / env contract ----------------------------------------

def process_table(job_name: str, runtime_id: str, spec: TPUJobSpec) -> List[Tuple[str, int, str, int]]:
    """Ordered (role, index, dns_name, port) for every process in the job.

    The analogue of the reference's ClusterSpec name map
    (training.go:103-118), with a stable global ordering: replica sets in
    spec order, indices within. The reference computed DMLC_NUM_SERVER /
    DMLC_NUM_WORKER by scanning replica sets the same way
    (replicas.go:215-233).
    """
    table = []
    for rs in spec.replica_specs:
        for i in range(rs.replicas):
            table.append(
                (
                    rs.tpu_replica_type,
                    i,
                    gen_general_name(job_name, rs.tpu_replica_type, runtime_id, i),
                    int(rs.tpu_port or 0),
                )
            )
    return table


def coordinator_address(job_name: str, runtime_id: str, spec: TPUJobSpec) -> Tuple[str, int]:
    """(dns, port) of the jax.distributed coordinator.

    SCHEDULER[0] if a SCHEDULER role exists (compat mode), else WORKER[0].
    This fixes the reference's hardcoded ``Replicas[0]``
    (replicas.go:240-243), which silently mis-pointed jobs whose scheduler
    was not listed first.
    """
    chosen: Optional[TPUReplicaSpec] = None
    for rs in spec.replica_specs:
        if rs.tpu_replica_type == TPUReplicaType.SCHEDULER:
            chosen = rs
            break
    if chosen is None:
        for rs in spec.replica_specs:
            if rs.tpu_replica_type == TPUReplicaType.WORKER:
                chosen = rs
                break
    if chosen is None:
        chosen = spec.replica_specs[0]
    return (
        gen_general_name(job_name, chosen.tpu_replica_type, runtime_id, 0),
        int(chosen.tpu_port or 0),
    )


class EnvContext:
    """Job-wide topology computed ONCE per sync and threaded through every
    replica's env build. Without it, each of the N pod specs rebuilt the
    full process table and rescanned it linearly for its own process id —
    an O(N²) env-build per gang sync that dominated pod-spec construction
    at megascale replica counts."""

    __slots__ = ("table", "coord", "process_index", "workers")

    def __init__(self, job_name: str, runtime_id: str, spec: TPUJobSpec):
        self.table = process_table(job_name, runtime_id, spec)
        self.coord = coordinator_address(job_name, runtime_id, spec)
        self.process_index = {
            (role, i): gi for gi, (role, i, _dns, _p) in enumerate(self.table)
        }
        self.workers = [entry for entry in self.table
                        if entry[0] == TPUReplicaType.WORKER]


def build_replica_env(
    job_name: str,
    runtime_id: str,
    spec: TPUJobSpec,
    replica_type: str,
    index: int,
    attempt: int = 0,
    ctx: Optional[EnvContext] = None,
) -> Dict[str, str]:
    """The env contract injected into the ``tpu`` container — the TPU-native
    replacement for the six ``DMLC_*`` vars (ref: replicas.go:235-260).

    Single-slice: all workers share one jax.distributed group.
    Multi-slice (spec.num_slices > 1): workers partition into equal slices;
    ``TPU_WORKER_*`` becomes slice-local and ``MEGASCALE_*`` carries the
    cross-slice DCN discovery info.

    ``ctx`` carries the precomputed job topology; sync loops build it once
    and pass it per replica. Omitting it computes a fresh one (single-pod
    call sites).
    """
    if ctx is None:
        ctx = EnvContext(job_name, runtime_id, spec)
    table = ctx.table
    coord_dns, coord_port = ctx.coord
    process_id = ctx.process_index[(replica_type, index)]
    workers = ctx.workers

    env = {
        "TPUJOB_NAME": job_name,
        "TPUJOB_RUNTIME_ID": runtime_id,
        "TPUJOB_REPLICA_TYPE": replica_type.lower(),
        "TPUJOB_REPLICA_INDEX": str(index),
        "TPUJOB_ATTEMPT": str(attempt),
        # The coordinator port rides inside the address — a separate
        # JAX_COORDINATOR_PORT var was injected for years but read by
        # nothing (payload or JAX; found by the env-contract analyzer).
        "JAX_COORDINATOR_ADDRESS": f"{coord_dns}:{coord_port}",
        "JAX_PROCESS_ID": str(process_id),
        "JAX_NUM_PROCESSES": str(len(table)),
    }
    if spec.tpu_topology:
        env["TPU_TOPOLOGY"] = spec.tpu_topology
    if spec.checkpoint_dir:
        env["TPU_CHECKPOINT_DIR"] = spec.checkpoint_dir
    if spec.profile_dir:
        env["TPU_PROFILE_DIR"] = spec.profile_dir
    cache = spec.compilation_cache
    if cache is not None and cache.enabled:
        # Warm-restart fast path: JAX reads JAX_COMPILATION_CACHE_DIR
        # natively; the TPUJOB_CACHE_* mirror lets the payload bootstrap
        # distinguish operator-wired caching (and log/force the min-entry
        # knobs) from an ambient developer env var.
        env["JAX_COMPILATION_CACHE_DIR"] = cache.path
        env["TPUJOB_CACHE_ENABLED"] = "1"
        env["TPUJOB_CACHE_PATH"] = cache.path
        env["TPUJOB_CACHE_MEDIUM"] = cache.medium
    store = spec.store
    if store is not None and store.uri:
        # Remote warm-start store (payload/warmstore.py consumes): write-
        # behind checkpoint/cache uploads + the rendezvous-overlapped
        # prefetch that makes a FRESH-node restart warm.
        env["TPUJOB_STORE_BACKEND"] = store.backend
        env["TPUJOB_STORE_URI"] = store.uri
        env["TPUJOB_STORE_PARALLELISM"] = str(store.upload_parallelism)
        env["TPUJOB_STORE_PREFETCH"] = "1" if store.prefetch else "0"
        if store.keep_snapshots:
            # Retention GC: the write-behind worker keeps only the newest
            # N verified snapshots remotely (payload/warmstore.py reads).
            env["TPUJOB_STORE_KEEP"] = str(store.keep_snapshots)
    if spec.mode == JobMode.SERVE:
        # Serving mode (payload/serve.py consumes): the mode flag, the
        # hot-reload watch cadence, and the HTTP ingress port — the SAME
        # port the replica's readiness-gated Service targets, so routed
        # traffic lands on the payload's POST /v1/decode endpoint (serve
        # replicas form no jax.distributed group, so the port the trainer
        # would spend on the coordinator is free for ingress). Scaling
        # knobs (min/max/target) stay controller-side — the payload only
        # reports traffic.
        env["TPUJOB_SERVE"] = "1"
        sv = spec.serving
        env["TPUJOB_SERVE_RELOAD_POLL"] = str(
            sv.reload_poll_seconds if sv is not None
            else DEFAULT_SERVE_RELOAD_POLL)
        env["TPUJOB_SERVE_PORT"] = str(
            table[process_id][3] or DEFAULT_TPU_PORT)
    trace = spec.step_trace
    if trace is not None:
        # Data-plane flight recorder (payload/steptrace.py consumes): the
        # recorder is on by default without any env; the block is only
        # injected to tune the ring size or opt out. stragglerRatio is
        # controller-side (the detector compares heartbeats), so it never
        # rides the pod env.
        env["TPUJOB_STEPTRACE_ENABLED"] = "1" if trace.enabled else "0"
        env["TPUJOB_STEPTRACE_BUFFER"] = str(trace.buffer_steps)
    dp = spec.data_plane
    if dp is not None:
        # Self-tuning data plane (payload/autotune.py consumes): the
        # block's presence activates the runtime (background host
        # pipeline + knob reporting); prefetchDepth 0 = auto. The
        # autotune sub-block additionally wires the closed-loop
        # controller's bounds and window.
        env["TPUJOB_DATAPLANE_PREFETCH_DEPTH"] = str(dp.prefetch_depth)
        at = dp.autotune
        if at is not None:
            env["TPUJOB_DATAPLANE_AUTOTUNE"] = "1" if at.enabled else "0"
            env["TPUJOB_DATAPLANE_MIN_DEPTH"] = str(at.min_depth)
            env["TPUJOB_DATAPLANE_MAX_DEPTH"] = str(at.max_depth)
            env["TPUJOB_DATAPLANE_WINDOW_STEPS"] = str(at.window_steps)

    if replica_type == TPUReplicaType.WORKER and workers:
        if spec.mode == JobMode.SERVE:
            # Serve replicas are INDEPENDENT decode servers: no
            # cross-replica JAX process group, no MEGASCALE discovery.
            # JAX_PROCESS_ID keeps the global index (the replica's
            # heartbeat identity); JAX_NUM_PROCESSES=1 makes any
            # bootstrap.initialize a single-process no-op, and the
            # worker-hostname view collapses to the replica itself.
            env["JAX_NUM_PROCESSES"] = "1"
            env["TPU_WORKER_ID"] = "0"
            env["TPU_WORKER_HOSTNAMES"] = \
                gen_general_name(job_name, replica_type, runtime_id, index)
            return env
        num_slices = max(1, spec.num_slices)
        per_slice = max(1, len(workers) // num_slices)
        slice_id = index // per_slice
        slice_workers = workers[slice_id * per_slice : (slice_id + 1) * per_slice]
        env["TPU_WORKER_ID"] = str(index % per_slice)
        env["TPU_WORKER_HOSTNAMES"] = ",".join(dns for _r, _i, dns, _p in slice_workers)
        if num_slices > 1:
            # Megascale DCN discovery: slice 0's first worker coordinates.
            env["MEGASCALE_COORDINATOR_ADDRESS"] = workers[0][2]
            env["MEGASCALE_NUM_SLICES"] = str(num_slices)
            env["MEGASCALE_SLICE_ID"] = str(slice_id)
    return env


def headless_service_spec(job: Any) -> Dict[str, Any]:
    """Job-scoped headless Service selecting every WORKER pod — gives each
    pod a stable ``hostname.subdomain`` DNS record for megascale/DCN worker
    enumeration (TPU-native addition; the reference only had per-index
    ClusterIP Services, replicas.go:132-159)."""
    spec: TPUJobSpec = job.job_spec
    name = headless_service_name(job.name, spec.runtime_id)
    selector = labels_mod.job_labels(job.name, spec.runtime_id)
    port = 0
    for rs in spec.replica_specs:
        if rs.tpu_replica_type == TPUReplicaType.WORKER:
            port = int(rs.tpu_port or 0)
            break
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": name,
            "labels": dict(selector),
            "ownerReferences": [helper.as_owner(job.metadata)],
        },
        "spec": {
            "clusterIP": "None",
            "selector": selector,
            "ports": [{"name": PORT_NAME, "port": port or 8476}],
        },
    }


# --- The replica set --------------------------------------------------------

class TPUReplicaSet:
    """Reconciles one replica set's pods + services
    (ref: MXReplicaSet, replicas.go:45-79)."""

    def __init__(self, clientset: Any, recorder: Any, job: Any, spec: TPUReplicaSpec):
        """``job`` provides .name/.namespace/.metadata/.job_spec (the
        reference holds the same back-pointer, replicas.go:49-56).

        The constructor re-checks invariants validation already enforces
        (ref ctor: replicas.go:81-117) — defensively, since replica sets can
        be built from cached CRD objects that predate stricter validation.
        """
        if spec.tpu_port is None:
            raise ValueError("tpuPort can't be None")
        if spec.tpu_replica_type not in TPUReplicaType.ALL:
            raise ValueError(f"invalid replica type {spec.tpu_replica_type!r}")
        if spec.tpu_replica_type == TPUReplicaType.SCHEDULER and spec.replicas != 1:
            raise ValueError("SCHEDULER replica set must have exactly 1 replica")
        self.clientset = clientset
        self.recorder = recorder
        self.job = job
        self.spec = spec

    # -- identity ------------------------------------------------------------

    @property
    def replica_type(self) -> str:
        return self.spec.tpu_replica_type

    def labels(self) -> Dict[str, str]:
        return labels_mod.replica_labels(
            self.job.name, self.job.job_spec.runtime_id, self.replica_type
        )

    def index_labels(self, index: int, attempt: int = 0) -> Dict[str, str]:
        return labels_mod.index_labels(
            self.job.name, self.job.job_spec.runtime_id, self.replica_type, index, attempt
        )

    def gen_name(self, index: int) -> str:
        return gen_general_name(
            self.job.name, self.replica_type, self.job.job_spec.runtime_id, index
        )

    # -- services (ref: replicas.go:132-159, 538-568) -------------------------

    def service_spec_with_index(self, index: int) -> Dict[str, Any]:
        # Selector deliberately excludes the attempt label: the Service must
        # keep routing to replacement pods across whole-group restarts.
        selector = self.index_labels(index)
        selector.pop("attempt", None)
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": self.gen_name(index),
                "labels": self.index_labels(index),
                "ownerReferences": [helper.as_owner(self.job.metadata)],
            },
            "spec": {
                "selector": selector,
                "ports": [
                    {
                        "name": PORT_NAME,
                        "port": int(self.spec.tpu_port),
                        "targetPort": int(self.spec.tpu_port),
                    }
                ],
            },
        }

    @traced
    def create_service_with_index(self, index: int,
                                  emit_event: bool = True
                                  ) -> Optional[Dict[str, Any]]:
        """ref: replicas.go:132-159. A 409 AlreadyExists is benign — the
        snapshot this create was decided from can lag the apiserver, and
        Service names are deterministic, so the duplicate create means the
        child is already there (returns None in that case)."""
        svc = self.service_spec_with_index(index)
        try:
            created = self.clientset.services.create(self.job.namespace, svc)
        except errors.ApiError as e:
            if errors.is_already_exists(e):
                log.debug("service %s already exists (stale cache); skipping",
                          svc["metadata"]["name"])
                return None
            raise
        if self.recorder and emit_event:
            self.recorder.event(
                self.job, "Normal", "SuccessfulCreate",
                f"Created service: {svc['metadata']['name']}",
            )
        return created

    def missing_service_indices(self,
                                snapshot: Optional[ReplicaSnapshot] = None
                                ) -> List[int]:
        """Indices with no Service in the snapshot (zero RPCs; the reference
        issued one GET per index, replicas.go:538-568)."""
        snap = snapshot or self._fallback_snapshot()
        return [index for index in range(self.spec.replicas)
                if not snap.has_service(self.gen_name(index))]

    @traced
    def sync_services(self, snapshot: Optional[ReplicaSnapshot] = None) -> None:
        """Create-if-absent per index, classified against the snapshot and
        created across the bounded pool; one aggregated SuccessfulCreate
        event per sync (ref: replicas.go:538-568, minus the N GETs)."""
        missing = self.missing_service_indices(snapshot)
        created: List[int] = []  # list.append is atomic; pool-safe

        def create_one(i: int) -> None:
            if self.create_service_with_index(i, emit_event=False) is not None:
                created.append(i)

        run_creates([lambda i=i: create_one(i) for i in missing],
                    self._create_parallelism())
        # Count what was actually created, not what the (possibly stale)
        # snapshot thought was missing — N benign 409s must not produce a
        # "Created N service(s)" event.
        if created and self.recorder:
            self.recorder.event(
                self.job, "Normal", "SuccessfulCreate",
                f"Created {len(created)} {self.replica_type.lower()} "
                f"service(s)",
            )

    @traced
    def sync_services_gated(self, snapshot: ReplicaSnapshot,
                            ready_indices: set,
                            known_indices: set) -> None:
        """Serve-mode readiness gating: an index's Service is created
        while the index is READY (its payload posted a ``ready`` serving
        beat) and deleted only when it is KNOWN not-ready — an explicit
        not-ready beat (reload in flight) or expired beats (wedged
        replica); an index with NO evidence (absent from ``known``)
        keeps whatever Service it has, so an operator restart — or one
        replica's beat landing before its peers' — never drops a healthy
        fleet out of routing. Train mode never calls this
        (sync_services keeps the unconditional path)."""
        create = [i for i in self.missing_service_indices(snapshot)
                  if i in ready_indices]
        created: List[int] = []

        def create_one(i: int) -> None:
            if self.create_service_with_index(i, emit_event=False) is not None:
                created.append(i)

        run_creates([lambda i=i: create_one(i) for i in create],
                    self._create_parallelism())
        removed = 0
        for index in range(self.spec.replicas):
            if index in ready_indices or index not in known_indices:
                continue
            name = self.gen_name(index)
            if not snapshot.has_service(name):
                continue
            try:
                self.clientset.services.delete(self.job.namespace, name)
                removed += 1
            except errors.ApiError as e:
                if not errors.is_not_found(e):
                    log.warning("readiness gate: deleting service %s: %s",
                                name, e)
        if self.recorder and (created or removed):
            self.recorder.event(
                self.job, "Normal", "ServingEndpoints",
                f"readiness gate: {len(created)} service(s) added, "
                f"{removed} removed ({len(ready_indices)} replica(s) "
                f"ready)")

    def _create_parallelism(self) -> int:
        config = getattr(self.job, "config", None)
        return int(getattr(config, "create_parallelism",
                           DEFAULT_CREATE_PARALLELISM)
                   or DEFAULT_CREATE_PARALLELISM)

    def _fallback_snapshot(self) -> ReplicaSnapshot:
        """Snapshot for informer-less use (standalone replica-set calls):
        one pod LIST + one service LIST under this replica set's selector —
        constant read cost, where the per-index loops were O(N) RPCs."""
        return ReplicaSnapshot.from_clientset(
            self.clientset, self.job.namespace,
            labels_mod.to_selector(self.labels()),
        )

    # -- pods (ref: replicas.go:162-276, 481-535) -----------------------------

    def pod_spec_with_index(self, index: int, attempt: int = 0,
                            env_ctx: Optional[EnvContext] = None
                            ) -> Dict[str, Any]:
        """Build the pod manifest for one replica index
        (ref: CreatePodWithIndex, replicas.go:162-276)."""
        job_spec: TPUJobSpec = self.job.job_spec
        # ONE deepcopy of the user template; metadata/spec below are views
        # into that private copy (they were redundantly deep-copied a second
        # time from the already-copied template).
        template = copy.deepcopy(self.spec.template) or {}
        pod: Dict[str, Any] = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": template.get("metadata") or {},
            "spec": template.get("spec") or {},
        }
        md = pod["metadata"]
        md["name"] = gen_pod_name(
            self.job.name, self.replica_type, job_spec.runtime_id, index
        )
        user_labels = md.get("labels") or {}
        user_labels.update(self.index_labels(index, attempt))
        md["labels"] = user_labels
        md["ownerReferences"] = [helper.as_owner(self.job.metadata)]

        pod_spec = pod["spec"]
        # schedulerName passthrough (ref: types.go:61-62 → replicas.go:178)
        if job_spec.scheduler_name:
            pod_spec["schedulerName"] = job_spec.scheduler_name
        # Stable per-pod DNS behind the job's headless Service (TPU-native:
        # megascale DCN discovery resolves hostname.subdomain).
        pod_spec["hostname"] = self.gen_name(index)
        pod_spec["subdomain"] = headless_service_name(self.job.name, job_spec.runtime_id)
        # Whole-group restart: the operator owns restarts, so container
        # restarts must surface as pod failure, not kubelet-local restart
        # (SURVEY.md §5: a JAX group cannot survive member loss).
        if job_spec.restart_policy == RestartPolicy.WHOLE_GROUP:
            pod_spec["restartPolicy"] = "Never"

        env = build_replica_env(
            self.job.name, job_spec.runtime_id, job_spec,
            self.replica_type, index, attempt, ctx=env_ctx,
        )
        # Identity + telemetry sink (payload/heartbeat.py): the namespace
        # and — when the operator advertises one — the status-server URL
        # process 0 posts step heartbeats to.
        env["TPUJOB_NAMESPACE"] = self.job.namespace
        status_url = getattr(getattr(self.job, "config", None),
                             "status_url", "")
        if status_url:
            env["TPUJOB_STATUS_URL"] = status_url
        injected = False
        for container in pod_spec.get("containers") or []:
            # Only the magic container gets the contract (ref: replicas.go:235
            # injects only into the container named "mxnet").
            if container.get("name") != DEFAULT_CONTAINER_NAME:
                continue
            existing = container.setdefault("env", [])
            user_set = {e.get("name") for e in existing}
            for k, v in env.items():
                if k not in user_set:
                    existing.append({"name": k, "value": v})
            injected = True
        if not injected:
            raise ValueError(
                f"pod template has no container named {DEFAULT_CONTAINER_NAME!r}"
            )
        self._inject_cache_volume(pod_spec, job_spec)
        self._inject_node_exclusion(pod_spec, index)
        return pod

    def _inject_node_exclusion(self, pod_spec: Dict[str, Any],
                               index: int) -> None:
        """Straggler-replace support: when the owning TrainingJob
        recorded a node this replica's replacement must avoid (the
        flagged member's host), add a NotIn hostname anti-affinity so
        the re-created member lands elsewhere. Appended into EVERY
        existing nodeSelectorTerm — terms are OR'd, so only an
        exclusion present in each one actually holds."""
        excluded = getattr(self.job, "excluded_node", None)
        node = excluded(self.replica_type, index) if callable(excluded) \
            else None
        if not node:
            return
        aff = pod_spec.setdefault("affinity", {}) \
                      .setdefault("nodeAffinity", {})
        req = aff.setdefault(
            "requiredDuringSchedulingIgnoredDuringExecution", {})
        terms = req.setdefault("nodeSelectorTerms", [])
        expr = {"key": "kubernetes.io/hostname", "operator": "NotIn",
                "values": [node]}
        if not terms:
            terms.append({"matchExpressions": [expr]})
            return
        for term in terms:
            term.setdefault("matchExpressions", []).append(expr)

    @staticmethod
    def _inject_cache_volume(pod_spec: Dict[str, Any],
                             job_spec: TPUJobSpec) -> None:
        """Mount the persistent compilation-cache volume into the ``tpu``
        container (spec.compilationCache). Medium hostPath points at the
        same path on the node, so a whole-group restart landing on the same
        node deserializes attempt N-1's executables; emptyDir is the
        no-hostPath fallback (cache lives and dies with the pod). A user
        template that already defines the volume or mount name wins."""
        cache = job_spec.compilation_cache
        if cache is None or not cache.enabled:
            return
        volumes = pod_spec.setdefault("volumes", [])
        if not any(v.get("name") == CACHE_VOLUME_NAME for v in volumes):
            if cache.medium == CacheMedium.HOSTPATH:
                source: Dict[str, Any] = {"hostPath": {
                    "path": cache.path, "type": "DirectoryOrCreate"}}
            else:
                source = {"emptyDir": {}}
            volumes.append({"name": CACHE_VOLUME_NAME, **source})
        for container in pod_spec.get("containers") or []:
            if container.get("name") != DEFAULT_CONTAINER_NAME:
                continue
            mounts = container.setdefault("volumeMounts", [])
            if not any(m.get("name") == CACHE_VOLUME_NAME for m in mounts):
                mounts.append({"name": CACHE_VOLUME_NAME,
                               "mountPath": cache.path})

    @traced
    def create_pod_with_index(self, index: int, attempt: int = 0,
                              env_ctx: Optional[EnvContext] = None,
                              emit_event: bool = True) -> Dict[str, Any]:
        pod = self.pod_spec_with_index(index, attempt, env_ctx=env_ctx)
        created = self.clientset.pods.create(self.job.namespace, pod)
        if self.recorder and emit_event:
            self.recorder.event(
                self.job, "Normal", "SuccessfulCreate",
                f"Created pod: {pod['metadata']['name']}",
            )
        return created

    def pods_for_index(self, index: int, attempt: Optional[int] = None,
                       snapshot: Optional[ReplicaSnapshot] = None) -> List[dict]:
        """This replica index's pods. From the snapshot when one is given
        (zero RPCs); a direct label-selected LIST otherwise."""
        if snapshot is not None:
            return snapshot.pods_for(self.replica_type, index, attempt)
        sel_labels = self.index_labels(index)
        sel_labels.pop("attempt", None)
        selector = labels_mod.to_selector(sel_labels)
        if attempt is not None:
            selector += f",attempt={attempt}"
        return self.clientset.pods.list(self.job.namespace, label_selector=selector)

    def missing_pod_indices(self, attempt: int = 0,
                            snapshot: Optional[ReplicaSnapshot] = None
                            ) -> List[int]:
        """Indices that need a pod created for this generation — the single
        home of the live-pod filter shared by ``sync_pods`` and the
        TrainingJob's gang creation. Classified against the snapshot (the
        reference issued one pod LIST per index, replicas.go:481-535).

        Per-pod mode (the reference behavior): fully-failed pods are filtered
        out (ref: replicas.go:497 ``status.phase != Failed``) so a fresh pod
        with a new random suffix replaces them.
        Whole-group mode: a failed pod does NOT make its index "missing" —
        the group restart decision belongs to the TrainingJob, which bumps
        the attempt and deletes the whole generation.
        """
        snap = snapshot or self._fallback_snapshot()
        per_pod = self.job.job_spec.restart_policy != RestartPolicy.WHOLE_GROUP
        missing = []
        for index in range(self.spec.replicas):
            pods = snap.pods_for(self.replica_type, index, attempt)
            live = [
                p for p in pods
                if (p.get("status") or {}).get("phase") != "Failed"
                and not (p.get("metadata") or {}).get("deletionTimestamp")
            ]
            if live:
                continue
            if pods and not per_pod:
                continue  # failed generation member; restart logic decides
            missing.append(index)
        return missing

    @traced
    def sync_pods(self, attempt: int = 0,
                  snapshot: Optional[ReplicaSnapshot] = None) -> None:
        """Create-if-absent per index (ref: SyncPods, replicas.go:481-535),
        creates fanned across the bounded pool with one aggregated event.
        Gang semantics (all-or-none with rollback) live in the TrainingJob;
        this standalone path is plain create-if-absent."""
        missing = self.missing_pod_indices(attempt, snapshot)
        if not missing:
            return
        env_ctx = EnvContext(self.job.name, self.job.job_spec.runtime_id,
                             self.job.job_spec)
        run_creates(
            [lambda i=i: self.create_pod_with_index(
                i, attempt, env_ctx=env_ctx, emit_event=False)
             for i in missing],
            self._create_parallelism(),
        )
        if self.recorder:
            self.recorder.event(
                self.job, "Normal", "SuccessfulCreate",
                f"Created {len(missing)} {self.replica_type.lower()} "
                f"pod(s) for attempt {attempt}",
            )

    # -- delete (ref: replicas.go:279-342) ------------------------------------

    @traced
    def delete(self) -> None:
        """Delete this replica set's children. One pod DeleteCollection (the
        reference issued it twice — copy-paste bug, replicas.go:292-302),
        then the services by LABEL — never by index enumeration, which
        under-counts after an elastic shrink (a gang ganged at 4 of 8
        slices still owns the services its 8-wide attempt created)."""
        selector = labels_mod.to_selector(self.labels())
        try:
            self.clientset.pods.delete_collection(self.job.namespace, selector)
        except errors.ApiError as e:
            if not errors.is_not_found(e):
                log.warning("deleting pods for %s: %s", self.replica_type, e)
        try:
            services = self.clientset.services.list(self.job.namespace,
                                                    label_selector=selector)
        except errors.ApiError as e:
            log.warning("listing services for %s: %s", self.replica_type, e)
            services = []
        for svc in services:
            name = (svc.get("metadata") or {}).get("name", "")
            try:
                self.clientset.services.delete(self.job.namespace, name)
            except errors.ApiError as e:
                if not errors.is_not_found(e):
                    log.warning("deleting service %s: %s", name, e)

    @traced
    def delete_pods_for_attempt(self, attempt: int) -> None:
        """Whole-group restart support: delete one generation's pods, keep
        services (their selectors span attempts)."""
        selector = labels_mod.to_selector(self.labels()) + f",attempt={attempt}"
        self.clientset.pods.delete_collection(self.job.namespace, selector)

    # -- status (ref: replicas.go:345-478) ------------------------------------

    @staticmethod
    def replica_state_from_pod_list(pods: List[dict],
                                    container_name: str = DEFAULT_CONTAINER_NAME) -> str:
        """Classify one replica's state from its pod list
        (ref: replicaStatusFromPodList, replicas.go:345-398).

        Differences from the reference, per SURVEY.md quirks: an empty list
        is STARTING (the ref returned Running, replicas.go:358-360), and a
        retryably-terminated container reports STARTING (a replacement is
        coming) while a permanent non-zero exit reports FAILED — the
        exit-code contract from policy.py (training.go:172-208).
        """
        if not pods:
            return ReplicaState.STARTING
        newest = max(
            pods,
            key=lambda p: ((p.get("metadata") or {}).get("creationTimestamp") or "",
                           (p.get("metadata") or {}).get("name") or ""),
        )
        status = newest.get("status") or {}
        phase = status.get("phase", "")
        if phase == "Pending":
            return ReplicaState.STARTING

        statuses = [
            c for c in (status.get("containerStatuses") or [])
            if c.get("name") == container_name
        ]
        if not statuses:
            if phase == "Failed":
                # Kubelet-level failure with no container record: Evicted /
                # Preempted etc. are transient on TPU → a replacement (or
                # group restart) is coming, not a permanent failure.
                reason = (newest.get("status") or {}).get("reason", "")
                if reason in policy.RETRYABLE_POD_REASONS:
                    return ReplicaState.STARTING
                return ReplicaState.FAILED
            return {
                "Running": ReplicaState.RUNNING,
                "Succeeded": ReplicaState.SUCCEEDED,
            }.get(phase, ReplicaState.UNKNOWN)

        cs = statuses[0]
        state = cs.get("state") or {}
        # LastTerminationState override: a waiting (e.g. CrashLoopBackOff)
        # container is judged by how it last died (ref: replicas.go:372-388).
        terminated = state.get("terminated") or (cs.get("lastState") or {}).get("terminated")
        if "running" in state:
            return ReplicaState.RUNNING
        if terminated is not None:
            if policy.is_success(terminated):
                return ReplicaState.SUCCEEDED
            if policy.is_retryable_termination_state(terminated):
                return ReplicaState.STARTING
            return ReplicaState.FAILED
        if "waiting" in state:
            return ReplicaState.STARTING
        return ReplicaState.UNKNOWN

    def retryable_failure_info(self, attempt: int,
                               snapshot: Optional[ReplicaSnapshot] = None
                               ) -> Optional[Tuple[str, str]]:
        """(FailureKind, reason) of this generation's retryable failure, or
        None — the whole-group restart trigger, feeding the per-kind retry
        budgets and the ``status.failures`` ledger. Covers both a retryable
        container exit (128-255, not OOM) and kubelet-level failures with no
        container record at all (Evicted/Preempted/NodeLost — routine TPU
        slice preemption). In WHOLE_GROUP mode pods run with restartPolicy
        Never, so every such death surfaces as a Failed pod.

        When one generation holds BOTH kinds (a segfaulting worker often
        takes a SIGKILLed sibling down with it), application-kind evidence
        wins: the restart is billed to the stricter crash-loop budget, not
        the 4x preemption budget — otherwise a crash-looper whose crashes
        collaterally kill siblings would sidestep its own cap. Planned
        drain exits (160) sit between: a real crash outranks them (same
        collateral argument — a drained sibling of a segfaulter is still a
        crash), but a planned exit outranks raw preemption evidence so a
        gang that completed its cooperative drain is ledgered planned even
        when a straggler process was SIGKILLed at the deadline."""
        first_preemption: Optional[Tuple[str, str]] = None
        first_planned: Optional[Tuple[str, str]] = None
        snap = snapshot or self._fallback_snapshot()
        for index in range(self.spec.replicas):
            for pod in snap.pods_for(self.replica_type, index, attempt):
                info = policy.classify_pod_failure(pod, DEFAULT_CONTAINER_NAME)
                if info is None:
                    continue
                if info[0] == FailureKind.PLANNED:
                    if first_planned is None:
                        first_planned = info
                elif info[0] != FailureKind.PREEMPTION:
                    return info
                elif first_preemption is None:
                    first_preemption = info
        return first_planned or first_preemption

    def get_single_replica_status(self, index: int,
                                  attempt: Optional[int] = None,
                                  snapshot: Optional[ReplicaSnapshot] = None
                                  ) -> str:
        """ref: GetSingleReplicaStatus (replicas.go:400-434), minus the
        dead Get-by-name path (see module docstring)."""
        return self.replica_state_from_pod_list(
            self.pods_for_index(index, attempt, snapshot))

    @traced
    def get_status(self, attempt: Optional[int] = None,
                   snapshot: Optional[ReplicaSnapshot] = None
                   ) -> TPUReplicaStatus:
        """Roll up per-index states (ref: GetStatus, replicas.go:436-478),
        classified against one snapshot instead of N pod LISTs."""
        snap = snapshot or self._fallback_snapshot()
        counts: Dict[str, int] = {}
        for index in range(self.spec.replicas):
            st = self.get_single_replica_status(index, attempt, snap)
            counts[st] = counts.get(st, 0) + 1

        n = self.spec.replicas
        succeeded = counts.get(ReplicaState.SUCCEEDED, 0)
        running = counts.get(ReplicaState.RUNNING, 0)
        if counts.get(ReplicaState.FAILED, 0) > 0:
            state = ReplicaState.FAILED
        elif succeeded == n:
            state = ReplicaState.SUCCEEDED
        elif running + succeeded == n:
            state = ReplicaState.RUNNING
        elif running > 0 or counts.get(ReplicaState.STARTING, 0) > 0:
            state = ReplicaState.STARTING
        else:
            state = ReplicaState.UNKNOWN
        return TPUReplicaStatus(
            tpu_replica_type=self.replica_type, state=state, replicas_states=counts
        )
