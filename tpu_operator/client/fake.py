"""In-memory fake clientset for tests.

Reference parity: the generated fakes in
pkg/client/clientset/versioned/fake/clientset_generated.go and
typed/mxnet/v1alpha1/fake/fake_mxjob.go:42-124, plus
k8s.io/client-go/kubernetes/fake — the trio the reference's test strategy is
built on (SURVEY.md §4: fake clientsets are load-bearing; reconcile tests
create pods/services against the fake and assert on the results).

Hand-built rather than generated. Two deliberate upgrades over client-go's
fake noted in the reference's own tests:

- ``delete_collection`` is implemented (the client-go fake didn't support it,
  forcing the reference to defer delete coverage to E2E —
  replicas_test.go:203-209).
- ``watch`` streams real events through per-watcher queues, so informers can
  be tested in-process.

Every mutation bumps a monotonically increasing resourceVersion, and an
action log (``actions``) records (verb, resource, namespace, name) tuples for
assertions, like client-go's ``Actions()``.
"""

from __future__ import annotations

import copy
import queue
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from tpu_operator.client import errors
from tpu_operator.client.selectors import matches
from tpu_operator.util import lockdep


class Watch:
    """A cancellable watch stream yielding (event_type, object) pairs."""

    def __init__(self, q: "queue.Queue[Optional[Tuple[str, dict]]]",
                 on_stop: Callable[[], None]):
        self._q = q
        self._on_stop = on_stop
        self._stopped = False

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._on_stop()
            self._q.put(None)  # unblock consumer

    def __iter__(self) -> Iterator[Tuple[str, dict]]:
        while True:
            item = self._q.get()
            if item is None or self._stopped:
                return
            yield item


class FakeResourceClient:
    """Typed CRUD+watch over one namespaced resource kind."""

    def __init__(self, kind: str, clientset: "FakeClientset"):
        self.kind = kind
        self._cs = clientset
        # Both guarded by the clientset's ONE RLock: cross-resource
        # operations (close_watches, the global version counter) must see
        # a consistent world, so per-resource locks would be wrong.
        self._store: Dict[Tuple[str, str], dict] = {}  # guarded-by: _cs.lock
        self._watchers: List[Tuple[queue.Queue, str, Optional[str]]] = []  # (q, ns, selector); guarded-by: _cs.lock

    # -- helpers -------------------------------------------------------------

    def _key(self, namespace: str, obj_or_name: Any) -> Tuple[str, str]:
        name = obj_or_name if isinstance(obj_or_name, str) else (
            (obj_or_name.get("metadata") or {}).get("name", "")
        )
        return (namespace, name)

    def _notify_locked(self, event_type: str, obj: dict, namespace: str) -> None:
        # Caller holds self._cs.lock (the *_locked convention).
        # Deletion bumps the resourceVersion on the *event* object (real
        # apiserver semantics: the watch DELETED event carries a fresh RV),
        # so the event log stays ordered by the global version counter.
        if event_type == "DELETED":
            obj = copy.deepcopy(obj)
            obj.setdefault("metadata", {})["resourceVersion"] = str(
                self._cs.next_version())
        rv = int((obj.get("metadata") or {}).get("resourceVersion") or 0)
        self._cs.log_event(rv, self.kind, namespace, event_type, obj)
        lbls = (obj.get("metadata") or {}).get("labels") or {}
        for q, ns, selector in list(self._watchers):
            if ns not in ("", namespace):
                continue
            if selector and not matches(selector, lbls):
                continue
            q.put((event_type, copy.deepcopy(obj)))

    # -- CRUD ----------------------------------------------------------------

    def create(self, namespace: str, obj: dict) -> dict:
        with self._cs.lock:
            key = self._key(namespace, obj)
            if not key[1]:
                raise errors.ApiError(422, "Invalid", f"{self.kind} must have metadata.name")
            if key in self._store:
                raise errors.already_exists(self.kind, key[1])
            stored = copy.deepcopy(obj)
            md = stored.setdefault("metadata", {})
            md["namespace"] = namespace
            md.setdefault("uid", f"uid-{self._cs.next_version()}")
            md["resourceVersion"] = str(self._cs.next_version())
            self._store[key] = stored
            self._cs.record("create", self.kind, namespace, key[1])
            self._notify_locked("ADDED", stored, namespace)
            return copy.deepcopy(stored)

    def get(self, namespace: str, name: str) -> dict:
        with self._cs.lock:
            obj = self._store.get((namespace, name))
            if obj is None:
                raise errors.not_found(self.kind, name)
            self._cs.record("get", self.kind, namespace, name)
            return copy.deepcopy(obj)

    def list(self, namespace: str = "", label_selector: str = "") -> List[dict]:
        with self._cs.lock:
            self._cs.record("list", self.kind, namespace, "")
            out = []
            for (ns, _name), obj in sorted(self._store.items()):
                if namespace and ns != namespace:
                    continue
                lbls = (obj.get("metadata") or {}).get("labels") or {}
                if label_selector and not matches(label_selector, lbls):
                    continue
                out.append(copy.deepcopy(obj))
            return out

    def list_with_version(self, namespace: str = "",
                          label_selector: str = "") -> Tuple[List[dict], str]:
        """(items, list resourceVersion) — the list-envelope RV a real
        apiserver returns in ``metadata.resourceVersion``, which anchors a
        gap-free watch (reflector list-then-watch)."""
        with self._cs.lock:
            return (self.list(namespace, label_selector),
                    str(self._cs.current_version()))

    def update(self, namespace: str, obj: dict) -> dict:
        with self._cs.lock:
            key = self._key(namespace, obj)
            existing = self._store.get(key)
            if existing is None:
                raise errors.not_found(self.kind, key[1])
            incoming_rv = (obj.get("metadata") or {}).get("resourceVersion")
            current_rv = (existing.get("metadata") or {}).get("resourceVersion")
            if incoming_rv and current_rv and incoming_rv != current_rv:
                raise errors.conflict(
                    self.kind, key[1],
                    f"resourceVersion {incoming_rv} is stale (current {current_rv})",
                )
            stored = copy.deepcopy(obj)
            md = stored.setdefault("metadata", {})
            md["namespace"] = namespace
            md.setdefault("uid", (existing.get("metadata") or {}).get("uid", ""))
            md["resourceVersion"] = str(self._cs.next_version())
            self._store[key] = stored
            self._cs.record("update", self.kind, namespace, key[1])
            self._notify_locked("MODIFIED", stored, namespace)
            return copy.deepcopy(stored)

    def update_status(self, namespace: str, obj: dict) -> dict:
        """Status-subresource write; merges only .status onto the stored object."""
        with self._cs.lock:
            key = self._key(namespace, obj)
            existing = self._store.get(key)
            if existing is None:
                raise errors.not_found(self.kind, key[1])
            existing = copy.deepcopy(existing)
            existing["status"] = copy.deepcopy(obj.get("status") or {})
            existing["metadata"]["resourceVersion"] = str(self._cs.next_version())
            self._store[key] = existing
            self._cs.record("update_status", self.kind, namespace, key[1])
            self._notify_locked("MODIFIED", existing, namespace)
            return copy.deepcopy(existing)

    def delete(self, namespace: str, name: str, options: Optional[dict] = None) -> None:
        with self._cs.lock:
            key = (namespace, name)
            obj = self._store.pop(key, None)
            if obj is None:
                raise errors.not_found(self.kind, name)
            self._cs.record("delete", self.kind, namespace, name)
            self._notify_locked("DELETED", obj, namespace)

    def delete_collection(self, namespace: str, label_selector: str = "") -> int:
        """Delete all matching objects; returns count. (The reference's fake
        lacked this — replicas_test.go:203-209.)"""
        with self._cs.lock:
            victims = []
            for (ns, name), obj in list(self._store.items()):
                if namespace and ns != namespace:
                    continue
                lbls = (obj.get("metadata") or {}).get("labels") or {}
                if label_selector and not matches(label_selector, lbls):
                    continue
                victims.append(((ns, name), obj))
            for key, obj in victims:
                del self._store[key]
                self._cs.record("delete", self.kind, key[0], key[1])
                self._notify_locked("DELETED", obj, key[0])
            return len(victims)

    # -- watch ---------------------------------------------------------------

    def watch(self, namespace: str = "", label_selector: str = "",
              resource_version: str = "") -> Watch:
        """Watch from "now" (no ``resource_version``) or from just after a
        given RV — replaying retained events with newer RVs first, exactly
        the apiserver contract. An RV older than the bounded event log's
        horizon raises **410 Gone** (errors.expired): the caller cannot be
        given a gap-free stream and must re-list. ``"0"`` means "any
        version" (K8s special case: never 410s, no replay guarantee)."""
        q: "queue.Queue[Optional[Tuple[str, dict]]]" = queue.Queue()
        entry = (q, namespace, label_selector or None)
        with self._cs.lock:
            if resource_version and resource_version != "0":
                try:
                    since = int(resource_version)
                except ValueError:
                    # Real apiservers answer 400, not a dropped connection.
                    raise errors.ApiError(
                        400, "BadRequest",
                        f"invalid resourceVersion {resource_version!r}")
                if since < self._cs.evicted_through():
                    raise errors.expired(
                        self.kind,
                        f"resourceVersion {resource_version} is too old "
                        f"(oldest retained: {self._cs.evicted_through()})")
                for rv, kind, ns, ev, obj in self._cs.retained_events():
                    if kind != self.kind or rv <= since:
                        continue
                    if namespace and ns != namespace:
                        continue
                    lbls = (obj.get("metadata") or {}).get("labels") or {}
                    if label_selector and not matches(label_selector, lbls):
                        continue
                    q.put((ev, copy.deepcopy(obj)))
            self._watchers.append(entry)

        def _unregister() -> None:
            with self._cs.lock:
                if entry in self._watchers:
                    self._watchers.remove(entry)

        return Watch(q, _unregister)


class FakeClientset:
    """The full fake clientset: pods, services, events, endpoints, leases,
    and the TPUJob CRD (ref: fake.NewSimpleClientset +
    fake/clientset_generated.go)."""

    # Watch-event history window (replay for RV-anchored watches). Real
    # apiservers bound theirs by etcd compaction + a watch cache; anything
    # older answers 410 Gone. Small enough that tests can actually age an
    # RV out and exercise the informer's 410 re-list path.
    EVENT_LOG_SIZE = 256

    def __init__(self) -> None:
        import collections

        self.lock = lockdep.rlock("FakeClientset.lock")
        # Optional metrics registry (controller.statusserver.Metrics):
        # when attached, every recorded action ticks
        # ``api_requests_total{verb,resource}`` — same ledger the REST
        # client maintains, so API-budget assertions and the control-plane
        # bench read one metric regardless of transport.
        self.metrics: Optional[Any] = None
        # Starts at 1, NOT 0: a real apiserver never hands out
        # resourceVersion "0" — it is the client-side "any version"
        # sentinel, and our own watch() honors that meaning (no replay
        # guarantee). A pristine store listing at version 0 therefore
        # anchored reflectors on "0", silently degrading their watch to
        # from-now and swallowing every event raced into the
        # list→watch-open window — at fleet burst rates that lost ~25%
        # of submitted jobs until the next resync (caught by
        # bench.py --fleet).
        self._version = 1  # guarded-by: lock
        self._events: "collections.deque" = collections.deque(
            maxlen=self.EVENT_LOG_SIZE)  # guarded-by: lock
        self._evicted_through = 0  # highest RV ever dropped from _events; guarded-by: lock
        self.actions: List[Tuple[str, str, str, str]] = []  # guarded-by: lock
        # Soak benches disable the audit log: real apiservers keep no
        # such log, and at 10k-pod scale its per-request tuples are
        # long-lived small allocations scattered through the churn —
        # they pin allocator arenas far beyond their own size and read
        # as RSS growth that no operator code caused.
        self.record_actions = True
        self.pods = FakeResourceClient("Pod", self)
        self.services = FakeResourceClient("Service", self)
        self.events = FakeResourceClient("Event", self)
        self.endpoints = FakeResourceClient("Endpoints", self)
        self.leases = FakeResourceClient("Lease", self)
        self.configmaps = FakeResourceClient("ConfigMap", self)
        self.tpujobs = FakeResourceClient("TPUJob", self)
        # Cluster-scoped in real K8s; the fake namespaces everything, and
        # the node-inventory informer lists with namespace "" (= all).
        self.nodes = FakeResourceClient("Node", self)

    def next_version(self) -> int:
        # Reentrant under the resource clients' CRUD lock; ALSO safe for
        # direct callers (tests) that hold nothing — the unlocked version
        # relied on every caller already being inside the RLock, which
        # nothing enforced (concurrency-analyzer finding).
        with self.lock:
            self._version += 1
            return self._version

    def current_version(self) -> int:
        with self.lock:
            return self._version

    def log_event(self, rv: int, kind: str, namespace: str, event_type: str,
                  obj: dict) -> None:
        with self.lock:
            if len(self._events) == self._events.maxlen:
                self._evicted_through = max(self._evicted_through,
                                            self._events[0][0])
            self._events.append((rv, kind, namespace, event_type,
                                 copy.deepcopy(obj)))

    def retained_events(self):
        with self.lock:
            return list(self._events)

    def evicted_through(self) -> int:
        """Highest resourceVersion evicted from the bounded event log: a
        watch anchored at or below this cannot be gap-free → 410."""
        with self.lock:
            return self._evicted_through

    def close_watches(self) -> None:
        """Terminate every open watch stream (unblocks consumers waiting on
        quiet resources). The apiserver harness calls this on shutdown so
        handler threads parked in a watch iteration always exit."""
        for client in (self.pods, self.services, self.events, self.endpoints,
                       self.leases, self.configmaps, self.tpujobs,
                       self.nodes):
            with self.lock:
                watchers = list(client._watchers)
            for q, _ns, _sel in watchers:
                q.put(None)

    def record(self, verb: str, resource: str, namespace: str, name: str) -> None:
        if self.record_actions:
            with self.lock:
                self.actions.append((verb, resource, namespace, name))
        if self.metrics is not None:
            self.metrics.inc("api_requests_total",
                             labels={"verb": verb, "resource": resource})

    def clear_actions(self) -> None:
        with self.lock:
            self.actions.clear()
