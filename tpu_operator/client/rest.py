"""Kubernetes REST client, from scratch on the standard library.

Reference parity: the generated typed clientset
(pkg/client/clientset/versioned/typed/mxnet/v1alpha1/mxjob.go:37-47 —
CRUD + Watch + Patch over the apiserver REST API) plus the kubernetes and
apiextensions clientsets the server creates (cmd/mx-operator/app/server.go:155-173).
The reference vendors 88 MB of client-go for this; the operator's actual
needs are six resource kinds with CRUD + watch + label selection, which this
module provides in one file over ``http.client``.

Wire behavior:
- JSON bodies both ways; non-2xx responses decode the Kubernetes ``Status``
  body into :class:`tpu_operator.client.errors.ApiError`, so call sites share
  one error model with the fake clientset.
- ``watch`` issues ``GET ...?watch=true`` and yields (type, object) pairs
  from the chunked JSON-lines stream; ``resourceVersion`` anchors the stream
  when given. The returned object matches the fake's Watch protocol
  (iterable + ``stop()``), which is what lets informers run unchanged
  against either.
- Auth: bearer token, client TLS certs, or insecure HTTP for tests — all
  resolved by util/k8sutil.py, mirroring the reference's
  kubeconfig-or-in-cluster resolution (pkg/util/k8sutil/k8sutil.go:50-74).
"""

from __future__ import annotations

import http.client
import json
import random
import socket
import ssl
import time
import urllib.parse
from dataclasses import dataclass, field
from http.client import HTTPConnection, HTTPSConnection
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from tpu_operator.client import errors

# Sentinel distinguishing "use the config default" from an explicit None
# (= no socket timeout, required for long-lived watch streams).
_DEFAULT_TIMEOUT = object()

# Verbs safe to replay blindly: repeating a read (or a delete — the second
# attempt just 404s) cannot double-apply anything, unlike POST/PUT where the
# first attempt may have landed before the connection died.
_IDEMPOTENT_VERBS = frozenset({"GET", "HEAD", "DELETE"})

# Status codes worth retrying on idempotent verbs: throttling and transient
# server-side failures. 4xx other than 429 are the caller's bug; 410 Gone is
# a watch-protocol signal the informer must see, never retried here.
_RETRYABLE_CODES = frozenset({429, 500, 502, 503, 504})

# Ceiling on a server-supplied Retry-After: the header is honored (it beats
# blind jitter) but must not let a hostile or misconfigured proxy park a
# controller thread for an hour per retry.
RETRY_AFTER_CAP = 30.0


@dataclass
class RestConfig:
    """Connection parameters (client-go's rest.Config equivalent)."""

    host: str  # e.g. "https://10.0.0.1:443" or "http://127.0.0.1:8001"
    bearer_token: str = ""
    ca_cert_file: str = ""
    client_cert_file: str = ""
    client_key_file: str = ""
    insecure_skip_tls_verify: bool = False
    timeout: float = 30.0
    extra_headers: Dict[str, str] = field(default_factory=dict)
    # Bounded retry for transient failures (connection reset, 429, 5xx) on
    # idempotent verbs; 0 restores the old one-shot behavior.
    max_retries: int = 3
    retry_base_delay: float = 0.25  # doubles per retry, full jitter applied
    retry_max_delay: float = 2.0

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.host.startswith("https"):
            return None
        ctx = ssl.create_default_context(
            cafile=self.ca_cert_file or None
        )
        if self.insecure_skip_tls_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if self.client_cert_file:
            ctx.load_cert_chain(self.client_cert_file, self.client_key_file or None)
        return ctx


class _StreamWatch:
    """Watch over a live HTTP chunked-response stream. Iterable of
    (event_type, object); ``stop()`` closes the socket, unblocking the
    consumer (same protocol as client.fake.Watch)."""

    def __init__(self, response: Any, connection: Any):
        self._resp = response
        self._conn = connection
        self._stopped = False

    def stop(self) -> None:
        """Unblock the consumer from another thread. MUST NOT call
        ``conn.close()``/``response.close()`` here: closing the buffered
        response reader needs a lock the blocked reader thread holds
        (observed as a hard deadlock under faulthandler). ``shutdown()`` on
        the raw socket deterministically wakes the reader, which then closes
        the connection from its own thread."""
        self._stopped = True
        sock = getattr(self._conn, "sock", None)
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def __iter__(self) -> Iterator[Tuple[str, dict]]:
        buf = b""
        try:
            while not self._stopped:
                chunk = self._resp.read1(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        event = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    yield event.get("type", ""), event.get("object", {})
        except (OSError, ssl.SSLError, socket.timeout, http.client.HTTPException):
            # Stream torn down — stop() shut the socket, or the server closed
            # the chunked response mid-read (surfaces as IncompleteRead, an
            # HTTPException, NOT an OSError). Either way this is a clean
            # stream end: the reflector above re-lists and re-watches.
            return
        finally:
            # Consumer-side close: safe here (same thread as the reader).
            try:
                self._conn.close()
            except OSError:
                pass


class RestClient:
    """Low-level request runner; one connection per call (watch holds its
    own), so it is thread-safe without pooling complexity.

    Idempotent verbs (GET/HEAD/DELETE — list, get, delete, watch open) get
    bounded retry with jittered exponential backoff on transient failures:
    connection resets/timeouts, 429 (honoring ``Retry-After``), and 5xx.
    POST/PUT are never replayed — the first attempt may have been applied
    before the failure. Each retry ticks ``api_request_retries_total`` when
    a metrics registry is attached (``metrics`` is assigned post-construction
    by the server bootstrap, once the controller's registry exists)."""

    def __init__(self, config: RestConfig, metrics: Optional[Any] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None):
        self.config = config
        self.metrics = metrics
        self._sleep = sleep
        self._rng = rng or random.Random()
        parsed = urllib.parse.urlparse(config.host)
        self._https = parsed.scheme == "https"
        self._netloc = parsed.netloc or parsed.path
        self._ctx = config.ssl_context()

    def _connect(self, timeout: Any = _DEFAULT_TIMEOUT) -> Any:
        timeout = self.config.timeout if timeout is _DEFAULT_TIMEOUT else timeout
        if self._https:
            return HTTPSConnection(self._netloc, context=self._ctx, timeout=timeout)
        return HTTPConnection(self._netloc, timeout=timeout)

    def _headers(self) -> Dict[str, str]:
        headers = {"Accept": "application/json", "Content-Type": "application/json"}
        if self.config.bearer_token:
            headers["Authorization"] = f"Bearer {self.config.bearer_token}"
        headers.update(self.config.extra_headers)
        return headers

    # -- retry plumbing --------------------------------------------------------

    def _retry_delay(self, attempt: int,
                     retry_after: Optional[float]) -> float:
        """Server-directed wait (429 Retry-After) or full-jitter exponential
        backoff: uniform in (0, min(base * 2^attempt, cap)] — the AWS
        full-jitter shape, which decorrelates a thundering herd of
        controllers hitting one throttled apiserver."""
        if retry_after is not None:
            return min(retry_after, RETRY_AFTER_CAP)
        cap = min(self.config.retry_base_delay * (2 ** attempt),
                  self.config.retry_max_delay)
        return cap * self._rng.random()

    def _run_with_retry(self, method: str, once: Callable[[], Any]) -> Any:
        attempt = 0
        while True:
            try:
                return once()
            except errors.ApiError as e:
                if (method not in _IDEMPOTENT_VERBS
                        or e.code not in _RETRYABLE_CODES
                        or attempt >= self.config.max_retries):
                    raise
                delay = self._retry_delay(attempt, e.retry_after)
            except (OSError, http.client.HTTPException):
                # Connection-level failure before a response arrived
                # (reset, refused, timeout, truncated status line).
                if (method not in _IDEMPOTENT_VERBS
                        or attempt >= self.config.max_retries):
                    raise
                delay = self._retry_delay(attempt, None)
            attempt += 1
            if self.metrics is not None:
                self.metrics.inc("api_request_retries_total")
            self._sleep(delay)

    # -- verbs -----------------------------------------------------------------

    def request(self, method: str, path: str,
                params: Optional[Dict[str, str]] = None,
                body: Optional[dict] = None,
                verb: str = "", resource: str = "") -> Any:
        if params:
            path = f"{path}?{urllib.parse.urlencode(params)}"
        self._count(verb or method.lower(), resource)
        return self._run_with_retry(
            method, lambda: self._request_once(method, path, body))

    def _count(self, verb: str, resource: str) -> None:
        """One tick of ``api_requests_total{verb,resource}`` per logical
        request (retries are counted separately) — the same ledger the fake
        clientset maintains, so API-budget accounting is transport-agnostic."""
        if self.metrics is not None:
            self.metrics.inc("api_requests_total",
                             labels={"verb": verb, "resource": resource or "?"})

    def _request_once(self, method: str, path: str,
                      body: Optional[dict]) -> Any:
        conn = self._connect()
        try:
            conn.request(
                method, path,
                body=json.dumps(body) if body is not None else None,
                headers=self._headers(),
            )
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 300:
                raise _status_error(resp.status, data,
                                    resp.getheader("Retry-After"))
            return json.loads(data) if data else None
        finally:
            conn.close()

    def stream(self, path: str, params: Dict[str, str],
               resource: str = "") -> _StreamWatch:
        """Open a watch stream (no read timeout — watches are long-lived).
        The *open* is retried like any idempotent GET (watch re-open races
        an apiserver restart constantly); an established stream's errors
        stay the informer's to handle (re-list + re-watch)."""
        qs = urllib.parse.urlencode(params)
        self._count("watch", resource)
        return self._run_with_retry(
            "GET", lambda: self._stream_once(f"{path}?{qs}"))

    def _stream_once(self, path_qs: str) -> _StreamWatch:
        conn = self._connect(timeout=None)
        conn.request("GET", path_qs, headers=self._headers())
        resp = conn.getresponse()
        if resp.status >= 300:
            data = resp.read()
            retry_after = resp.getheader("Retry-After")
            conn.close()
            raise _status_error(resp.status, data, retry_after)
        return _StreamWatch(resp, conn)


def _status_error(code: int, data: bytes,
                  retry_after_header: Optional[str] = None) -> errors.ApiError:
    reason, message, status = "", "", {}
    try:
        status = json.loads(data)
        reason = status.get("reason", "")
        message = status.get("message", "")
    except (json.JSONDecodeError, AttributeError):
        message = data.decode("utf-8", "replace")[:500]
    # Delta-seconds Retry-After (the throttling form; HTTP-date is ignored)
    # rides along for the retry layer to honor on 429s.
    retry_after = None
    if retry_after_header:
        try:
            retry_after = max(0.0, float(retry_after_header))
        except ValueError:
            pass
    return errors.ApiError(code, reason, message, status,
                           retry_after=retry_after)


class RestResourceClient:
    """Typed CRUD + watch for one namespaced resource; the drop-in HTTP
    counterpart of client.fake.FakeResourceClient."""

    def __init__(self, rest: RestClient, api_prefix: str, resource: str, kind: str):
        self._rest = rest
        self._prefix = api_prefix  # "/api/v1" or "/apis/<group>/<version>"
        self.resource = resource
        self.kind = kind

    def _path(self, namespace: str, name: str = "") -> str:
        # Empty namespace means cluster-scoped (nodes) or all-namespaces
        # (list/watch): either way the un-prefixed collection path.
        if namespace:
            base = f"{self._prefix}/namespaces/{namespace}/{self.resource}"
        else:
            base = f"{self._prefix}/{self.resource}"
        return f"{base}/{name}" if name else base

    def create(self, namespace: str, obj: dict) -> dict:
        return self._rest.request("POST", self._path(namespace), body=obj,
                                  verb="create", resource=self.kind)

    def get(self, namespace: str, name: str) -> dict:
        return self._rest.request("GET", self._path(namespace, name),
                                  verb="get", resource=self.kind)

    def list(self, namespace: str = "", label_selector: str = "") -> List[dict]:
        params: Dict[str, str] = {}
        if label_selector:
            params["labelSelector"] = label_selector
        if namespace:
            path = self._path(namespace)
        else:
            path = f"{self._prefix}/{self.resource}"  # all namespaces
        result = self._rest.request("GET", path, params=params,
                                    verb="list", resource=self.kind)
        return (result or {}).get("items", [])

    def list_with_version(self, namespace: str = "",
                          label_selector: str = "") -> Tuple[List[dict], str]:
        """(items, list resourceVersion) from the list envelope's
        ``metadata.resourceVersion`` — what a reflector anchors its watch
        at for a gap-free list-then-watch (client-go Reflector semantics)."""
        params: Dict[str, str] = {}
        if label_selector:
            params["labelSelector"] = label_selector
        if namespace:
            path = self._path(namespace)
        else:
            path = f"{self._prefix}/{self.resource}"
        result = self._rest.request("GET", path, params=params,
                                    verb="list", resource=self.kind) or {}
        return (result.get("items", []),
                (result.get("metadata") or {}).get("resourceVersion", ""))

    def update(self, namespace: str, obj: dict) -> dict:
        name = (obj.get("metadata") or {}).get("name", "")
        return self._rest.request("PUT", self._path(namespace, name), body=obj,
                                  verb="update", resource=self.kind)

    def update_status(self, namespace: str, obj: dict) -> dict:
        name = (obj.get("metadata") or {}).get("name", "")
        return self._rest.request(
            "PUT", self._path(namespace, name) + "/status", body=obj,
            verb="update_status", resource=self.kind,
        )

    def delete(self, namespace: str, name: str, options: Optional[dict] = None) -> None:
        self._rest.request("DELETE", self._path(namespace, name), body=options,
                           verb="delete", resource=self.kind)

    def delete_collection(self, namespace: str, label_selector: str = "") -> int:
        params = {"labelSelector": label_selector} if label_selector else {}
        result = self._rest.request("DELETE", self._path(namespace),
                                    params=params,
                                    verb="delete", resource=self.kind)
        return len((result or {}).get("items", []))

    def watch(self, namespace: str = "", label_selector: str = "",
              resource_version: str = "") -> _StreamWatch:
        params: Dict[str, str] = {"watch": "true"}
        if label_selector:
            params["labelSelector"] = label_selector
        if resource_version:
            params["resourceVersion"] = resource_version
        return self._rest.stream(self._path(namespace) if namespace
                                 else f"{self._prefix}/{self.resource}", params,
                                 resource=self.kind)


class Clientset:
    """The full typed clientset over one RestConfig (ref: the three clients
    built at server.go:155-173 collapsed into one surface)."""

    def __init__(self, config: RestConfig):
        from tpu_operator.apis.tpujob.v1alpha1.types import (
            CRD_GROUP, CRD_KIND, CRD_KIND_PLURAL, CRD_VERSION,
        )

        self.rest = RestClient(config)
        core = "/api/v1"
        self.pods = RestResourceClient(self.rest, core, "pods", "Pod")
        self.services = RestResourceClient(self.rest, core, "services", "Service")
        self.events = RestResourceClient(self.rest, core, "events", "Event")
        self.endpoints = RestResourceClient(self.rest, core, "endpoints", "Endpoints")
        self.configmaps = RestResourceClient(self.rest, core, "configmaps", "ConfigMap")
        self.leases = RestResourceClient(
            self.rest, "/apis/coordination.k8s.io/v1", "leases", "Lease"
        )
        self.tpujobs = RestResourceClient(
            self.rest, f"/apis/{CRD_GROUP}/{CRD_VERSION}", CRD_KIND_PLURAL, CRD_KIND
        )
        # Cluster-scoped: the node-inventory informer lists/watches with
        # namespace "" so the path is the un-namespaced /api/v1/nodes.
        self.nodes = RestResourceClient(self.rest, core, "nodes", "Node")
