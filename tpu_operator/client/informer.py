"""Informers: list+watch reflection into a local cache with event handlers.

Reference parity: the generated shared-informer stack
(pkg/client/informers/externalversions/factory.go:79,111 and
listers/mxnet/v1alpha1/mxjob.go:29-90) as used by the controller: the
informer cache is the read path for every reconcile (controller.go:225
lister Get), event handlers feed the workqueue (controller.go:114-132), and
a 30 s resync re-delivers the world (server.go:85).

Hand-built equivalent: a ``Reflector`` thread lists then watches one
resource, maintaining a thread-safe ``Store`` keyed ``ns/name`` and
dispatching add/update/delete handlers; a resync timer re-dispatches updates
for all cached objects. ``SharedInformerFactory`` shares one informer per
resource kind across consumers (ref: factory.go:111 InformerFor).

Works identically over the fake clientset's in-memory watch streams and the
real apiserver watch (both yield (event_type, object) pairs), which is what
makes controller-level tests possible without a cluster.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpu_operator.util import lockdep

log = logging.getLogger(__name__)

DEFAULT_RESYNC_PERIOD = 30.0  # seconds (ref: server.go:85)

Handler = Callable[..., None]
# An index function maps one object to the index values it appears under
# (client-go's cache.IndexFunc).
IndexFunc = Callable[[Dict[str, Any]], List[str]]

# Built-in index names (ref: client-go's cache.NamespaceIndex idiom; these
# two are what turns every per-reconcile child lookup into a cache hit).
INDEX_OWNER_UID = "controller-uid"
INDEX_JOB = "job"


def object_key(obj: Dict[str, Any]) -> str:
    """``namespace/name`` cache key (client-go's MetaNamespaceKeyFunc)."""
    md = obj.get("metadata") or {}
    return f"{md.get('namespace', 'default')}/{md.get('name', '')}"


def index_by_controlling_tpujob_uid(obj: Dict[str, Any]) -> List[str]:
    """Index values: UIDs of the controlling TPUJob OwnerReference."""
    md = obj.get("metadata") or {}
    return [
        ref.get("uid", "")
        for ref in md.get("ownerReferences") or []
        if ref.get("kind") == "TPUJob" and ref.get("controller")
        and ref.get("uid")
    ]


def index_by_job_label(obj: Dict[str, Any]) -> List[str]:
    """Index values: ``namespace/job_name`` from the child's job label."""
    md = obj.get("metadata") or {}
    job = (md.get("labels") or {}).get("job_name", "")
    if not job:
        return []
    return [f"{md.get('namespace', 'default')}/{job}"]


def add_child_indexes(store: "Store") -> None:
    """Install the built-in pod/service indexes (owner UID + job label)."""
    store.add_index(INDEX_OWNER_UID, index_by_controlling_tpujob_uid)
    store.add_index(INDEX_JOB, index_by_job_label)


class Store:
    """Thread-safe object cache (the lister; ref: listers/.../mxjob.go:29-90)
    with client-go-style indexers: ``add_index`` registers an IndexFunc and
    ``by_index`` answers reads from the maintained inverted index, so a
    reconcile can fetch "all pods owned by job UID X" without scanning the
    store, let alone the apiserver."""

    def __init__(self) -> None:
        self._lock = lockdep.rlock("informer.Store._lock")
        self._items: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        self._indexers: Dict[str, IndexFunc] = {}  # guarded-by: _lock
        # index name -> index value -> {object key: object}
        self._indices: Dict[str, Dict[str, Dict[str, Dict[str, Any]]]] = {}  # guarded-by: _lock

    def get(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._items.get(f"{namespace}/{name}")

    def get_by_key(self, key: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._items.get(key)

    def list(self, namespace: str = "") -> List[Dict[str, Any]]:
        with self._lock:
            if not namespace:
                return list(self._items.values())
            prefix = f"{namespace}/"
            return [o for k, o in self._items.items() if k.startswith(prefix)]

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._items.keys())

    # -- indexers (ref: client-go cache.Indexer AddIndexers/ByIndex) ----------

    def add_index(self, name: str, fn: IndexFunc) -> None:
        """Register an index and backfill it over the current contents.
        Idempotent per name (re-registering replaces and rebuilds)."""
        with self._lock:
            self._indexers[name] = fn
            index: Dict[str, Dict[str, Dict[str, Any]]] = {}
            for key, obj in self._items.items():
                for value in fn(obj):
                    index.setdefault(value, {})[key] = obj
            self._indices[name] = index

    def by_index(self, name: str, value: str) -> List[Dict[str, Any]]:
        """All cached objects whose index ``name`` contains ``value``."""
        with self._lock:
            if name not in self._indexers:
                raise KeyError(f"unknown index {name!r}")
            return list(self._indices[name].get(value, {}).values())

    def _index_remove_locked(self, key: str, obj: Dict[str, Any]) -> None:
        for name, fn in self._indexers.items():
            index = self._indices[name]
            for value in fn(obj):
                bucket = index.get(value)
                if bucket is not None:
                    bucket.pop(key, None)
                    if not bucket:
                        del index[value]

    def _index_insert_locked(self, key: str, obj: Dict[str, Any]) -> None:
        for name, fn in self._indexers.items():
            for value in fn(obj):
                self._indices[name].setdefault(value, {})[key] = obj

    # -- mutation -------------------------------------------------------------

    def upsert(self, obj: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        with self._lock:
            key = object_key(obj)
            old = self._items.get(key)
            if old is not None:
                self._index_remove_locked(key, old)
            self._items[key] = obj
            self._index_insert_locked(key, obj)
            return old

    def delete(self, obj: Dict[str, Any]) -> None:
        with self._lock:
            key = object_key(obj)
            old = self._items.pop(key, None)
            if old is not None:
                self._index_remove_locked(key, old)

    def replace(self, objs: List[Dict[str, Any]]) -> None:
        with self._lock:
            self._items = {object_key(o): o for o in objs}
            for name, fn in self._indexers.items():
                index: Dict[str, Dict[str, Dict[str, Any]]] = {}
                for key, obj in self._items.items():
                    for value in fn(obj):
                        index.setdefault(value, {})[key] = obj
                self._indices[name] = index


@dataclass
class Listers:
    """The informer caches a reconcile reads from (client-go's listers
    bundle): every steady-state read is served here; the apiserver only
    sees writes."""

    tpujobs: Store
    pods: Store
    services: Store


class Informer:
    """One resource kind's reflector + cache + handler fan-out."""

    def __init__(self, resource_client: Any, namespace: str = "",
                 resync_period: float = DEFAULT_RESYNC_PERIOD):
        self._client = resource_client
        self._namespace = namespace
        self._resync_period = resync_period
        self.store = Store()
        # Mutated by add_event_handler — which a late informer_for() call
        # can run AFTER start(), i.e. concurrently with the reflector and
        # resync threads iterating it (found by the escape analyzer; an
        # unlocked list append raced the dispatch loop's iteration).
        self._handlers: List[Tuple[Optional[Handler], Optional[Handler], Optional[Handler]]] = []  # guarded-by: _lock
        self._synced = threading.Event()
        self._threads: List[threading.Thread] = []
        self._watch = None  # guarded-by: _lock
        self._lock = lockdep.lock("Informer._lock")

    def add_event_handler(self, on_add: Optional[Handler] = None,
                          on_update: Optional[Handler] = None,
                          on_delete: Optional[Handler] = None) -> None:
        """ref: controller.go:114-132 AddEventHandler(Add/Update/Delete)."""
        with self._lock:
            self._handlers.append((on_add, on_update, on_delete))

    def _handlers_snapshot(self) -> List[Tuple[Optional[Handler],
                                               Optional[Handler],
                                               Optional[Handler]]]:
        """Stable view for one dispatch (handlers registered mid-dispatch
        catch the NEXT event — the informer replays state on sync anyway)."""
        with self._lock:
            return list(self._handlers)

    def has_synced(self) -> bool:
        """ref: cache.WaitForCacheSync (controller.go:155)."""
        return self._synced.is_set()

    # -- run ------------------------------------------------------------------

    def start(self, stop_event: threading.Event) -> None:
        t = threading.Thread(target=self._run, args=(stop_event,), daemon=True,
                             name=f"informer-{getattr(self._client, 'kind', '?')}")
        t.start()
        self._threads.append(t)
        # ONE watch-stopper for the informer's lifetime (not one per
        # list/watch cycle — that leaked a parked thread per re-list): on
        # shutdown it tears down whichever stream is current.
        stopper = threading.Thread(
            target=self._stop_current_watch_on, args=(stop_event,),
            daemon=True, name="informer-watch-stopper",
        )
        stopper.start()
        self._threads.append(stopper)
        if self._resync_period > 0:
            rt = threading.Thread(target=self._resync_loop, args=(stop_event,),
                                  daemon=True, name="informer-resync")
            rt.start()
            self._threads.append(rt)

    def _run(self, stop_event: threading.Event) -> None:
        while not stop_event.is_set():
            try:
                self._list_and_watch(stop_event)
            except Exception as e:  # noqa: BLE001 — reflector must survive
                log.warning("reflector error (will re-list): %s", e)
                stop_event.wait(1.0)

    def _list_and_watch(self, stop_event: threading.Event) -> None:
        # Against a client that returns a list resourceVersion
        # (list_with_version — the real apiserver and the harness), this is
        # the client-go Reflector discipline: list, then watch anchored at
        # the list's RV, so the stream resumes exactly where the snapshot
        # ended — gap-free by construction. A 410 Gone on the anchored open
        # (RV already compacted out of the server's watch window) RE-LISTS
        # for a fresh anchor and retries — client-go's Relist-on-410.
        # Watching "from now" instead (the pre-fleet behavior) left a gap
        # between the stale snapshot and the new stream that only the next
        # resync healed: under a fleet-scale create burst that gap
        # swallowed ~25% of submitted jobs for the whole resync period
        # (caught by bench.py --fleet stalling with phase-None jobs and an
        # empty queue). The re-list is self-throttling — each retry pays a
        # full LIST — and every retry refreshes the snapshot, so progress
        # is made even while the event log churns.
        #
        # Clients without list RVs (bare fakes) keep the round-2 order —
        # watch opens BEFORE the list so no event falls in a gap between
        # the two; racing events are applied on top of the snapshot
        # (idempotent for a level-triggered consumer).
        from tpu_operator.client import errors

        objs, rv = None, ""
        lister = getattr(self._client, "list_with_version", None)
        if lister is not None:
            objs, rv = lister(self._namespace)
        if rv == "0":
            # "0" is the K8s "any version" sentinel, NOT a usable anchor:
            # a watch opened at it carries no replay guarantee, so
            # treating it as an anchor silently degraded to from-now and
            # lost every event raced into the list→open window. Fall to
            # the watch-before-list path below, which is gap-free for
            # unanchored streams.
            objs, rv = None, ""
        if rv:
            watch = None
            while watch is None:
                try:
                    watch = self._client.watch(self._namespace,
                                               resource_version=rv)
                except errors.ApiError as e:
                    if not errors.is_expired(e):
                        raise
                    log.info("anchored watch at RV %s got 410 Gone; "
                             "re-listing for a fresh anchor", rv)
                    if stop_event.is_set():
                        return
                    objs, rv = lister(self._namespace)
                    if not rv or rv == "0":
                        break  # no usable anchor any more
            if watch is None:
                objs = None
                watch = self._client.watch(self._namespace)
        else:
            # No list RV (server omitted it, or bare fake): discard any
            # pre-watch snapshot and keep the watch-BEFORE-list order — a
            # post-watch list closes the gap a from-now watch would leave.
            objs = None
            watch = self._client.watch(self._namespace)
        with self._lock:
            self._watch = watch
        if stop_event.is_set():  # raced shutdown between create and register
            watch.stop()
            return

        if objs is None:
            objs = self._client.list(self._namespace)
        self.store.replace(objs)
        for obj in objs:
            self._dispatch_add(obj)
        self._synced.set()
        for event_type, obj in watch:
            if stop_event.is_set():
                return
            if event_type == "ADDED":
                old = self.store.upsert(obj)
                if old is None:
                    self._dispatch_add(obj)
                else:
                    self._dispatch_update(old, obj)
            elif event_type == "MODIFIED":
                old = self.store.upsert(obj)
                self._dispatch_update(old, obj)
            elif event_type == "DELETED":
                self.store.delete(obj)
                self._dispatch_delete(obj)
            elif event_type == "BOOKMARK":
                # Progress marker only (carries just a resourceVersion);
                # nothing to dispatch — next cycle re-anchors off a fresh
                # list RV anyway.
                continue
            elif event_type == "ERROR":
                code = (obj or {}).get("code")
                if code == 410:
                    # The server compacted past our position mid-stream:
                    # the mandated recovery is a fresh list (immediately —
                    # this is an expected protocol event, not a fault).
                    log.info("watch stream expired (410 Gone in-stream); "
                             "re-listing")
                    return
                return  # unknown server error → re-list

    def _stop_current_watch_on(self, stop_event: threading.Event) -> None:
        stop_event.wait()
        with self._lock:
            watch = self._watch
        if watch is not None:
            watch.stop()

    def _resync_loop(self, stop_event: threading.Event) -> None:
        """Periodic re-list + delete-repair so missed edge cases self-heal
        (ref: 30 s resync, server.go:85). Unlike client-go's cache-only
        resync this re-lists from the source of truth, so an event lost to
        any race (including deletions) is repaired within one period instead
        of persisting forever.

        Unchanged objects are NOT re-dispatched: an object whose
        resourceVersion matches the cached copy carries no new information,
        and re-delivering ``update(obj, obj)`` for the whole world every
        period enqueued a full reconcile of every idle job — pure queue
        churn at O(jobs) per resync. Only objects with a differing (or
        absent) resourceVersion dispatch; the delete-repair sweep is kept
        in full."""
        while not stop_event.wait(self._resync_period):
            try:
                fresh = {object_key(o): o for o in self._client.list(self._namespace)}
            except Exception as e:  # noqa: BLE001 — transient API failure
                log.warning("resync re-list failed: %s", e)
                continue
            for key in self.store.keys():
                if key not in fresh:
                    gone = self.store.get_by_key(key)
                    if gone is not None:
                        self.store.delete(gone)
                        self._dispatch_delete(gone)
            for obj in fresh.values():
                old = self.store.get_by_key(object_key(obj))
                self.store.upsert(obj)
                if old is not None:
                    old_rv = (old.get("metadata") or {}).get("resourceVersion")
                    new_rv = (obj.get("metadata") or {}).get("resourceVersion")
                    if old_rv and old_rv == new_rv:
                        continue  # unchanged since last delivery
                self._dispatch_update(old if old is not None else obj, obj)

    # -- dispatch -------------------------------------------------------------

    def _dispatch_add(self, obj: Dict[str, Any]) -> None:
        for on_add, _u, _d in self._handlers_snapshot():
            if on_add:
                self._safe(on_add, obj)

    def _dispatch_update(self, old: Any, new: Dict[str, Any]) -> None:
        for _a, on_update, _d in self._handlers_snapshot():
            if on_update:
                self._safe(on_update, old, new)

    def _dispatch_delete(self, obj: Dict[str, Any]) -> None:
        for _a, _u, on_delete in self._handlers_snapshot():
            if on_delete:
                self._safe(on_delete, obj)

    @staticmethod
    def _safe(handler: Handler, *args: Any) -> None:
        try:
            handler(*args)
        except Exception as e:  # noqa: BLE001 — handlers must not kill the reflector
            log.exception("informer handler failed: %s", e)


class SharedInformerFactory:
    """One informer per resource kind, shared (ref: factory.go:79,111)."""

    def __init__(self, clientset: Any, namespace: str = "",
                 resync_period: float = DEFAULT_RESYNC_PERIOD):
        self._clientset = clientset
        self._namespace = namespace
        self._resync = resync_period
        self._informers: Dict[str, Informer] = {}
        self._started = False
        self._stop_event: Optional[threading.Event] = None

    @property
    def informers(self) -> Dict[str, "Informer"]:
        """Live view of the created informers (status server readiness)."""
        return self._informers

    def informer_for(self, resource: str,
                     namespace: Optional[str] = None) -> Informer:
        """One shared informer per resource kind. ``namespace`` overrides
        the factory default for cluster-scoped resources (nodes pass ""
        = all namespaces, which maps to the un-namespaced list/watch
        path); it only applies on first creation."""
        if resource not in self._informers:
            client = getattr(self._clientset, resource)
            ns = self._namespace if namespace is None else namespace
            inf = Informer(client, ns, self._resync)
            self._informers[resource] = inf
            if self._started and self._stop_event is not None:
                inf.start(self._stop_event)
        return self._informers[resource]

    def start(self, stop_event: threading.Event) -> None:
        """ref: go informerFactory.Start (server.go:91)."""
        self._started = True
        self._stop_event = stop_event
        for inf in self._informers.values():
            inf.start(stop_event)

    def wait_for_cache_sync(self, timeout: float = 30.0) -> bool:
        """ref: cache.WaitForCacheSync (controller.go:155)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        for inf in self._informers.values():
            remaining = deadline - _time.monotonic()
            if remaining <= 0 or not inf._synced.wait(remaining):
                return False
        return True
