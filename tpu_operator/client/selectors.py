"""Label-selector parsing and matching.

Both the fake clientset and the in-process apiserver need server-side label
selection; the real client only serializes selectors. Supports the equality
subset of Kubernetes selector grammar (``k=v,k2=v2``, ``k!=v``, bare ``k``),
which is all the operator uses (ref: trainer/labels.go ToSelector emits
``k=v`` pairs; hack/scripts/cleanup_clusters.sh uses a bare equality
selector).
"""

from __future__ import annotations

from typing import Any, Dict


def matches(selector: str, labels: Dict[str, Any] | None) -> bool:
    """True if `labels` satisfies the comma-separated equality selector."""
    labels = labels or {}
    selector = (selector or "").strip()
    if not selector:
        return True
    for term in selector.split(","):
        term = term.strip()
        if not term:
            continue
        if "!=" in term:
            k, v = term.split("!=", 1)
            if str(labels.get(k.strip())) == v.strip():
                return False
        elif "=" in term:
            k, v = term.split("=", 1)
            k = k.strip().rstrip("=")  # tolerate "==" form
            if k not in labels or str(labels[k]) != v.strip():
                return False
        else:
            if term not in labels:
                return False
    return True


def format_selector(labels: Dict[str, Any]) -> str:
    """Serialize a label map to ``k=v,...`` (ref: labels.go:28-33 ToSelector)."""
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
