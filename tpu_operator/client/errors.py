"""API error model + predicates.

Reference parity: the reference relies on k8s.io/apimachinery StatusError and
the predicates in pkg/util/k8sutil/k8sutil.go:76-82 (IsKubernetesResourceAlreadyExistError,
IsKubernetesResourceNotFoundError). Both the real REST client and the fake
clientset raise ``ApiError`` with the HTTP status code, so call sites use one
error model everywhere.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class ApiError(Exception):
    """A Kubernetes API error carrying the HTTP status code and Status body."""

    def __init__(self, code: int, reason: str = "", message: str = "",
                 status: Optional[Dict[str, Any]] = None,
                 retry_after: Optional[float] = None):
        self.code = code
        self.reason = reason or _default_reason(code)
        self.message = message
        self.status = status or {}
        # Delta-seconds Retry-After from a 429 response, for the retry
        # layer to honor; None everywhere else.
        self.retry_after = retry_after
        super().__init__(f"{self.code} {self.reason}: {message}")


def _default_reason(code: int) -> str:
    return {
        400: "BadRequest",
        401: "Unauthorized",
        403: "Forbidden",
        404: "NotFound",
        409: "Conflict",
        410: "Gone",
        422: "Invalid",
    }.get(code, "Unknown")


def not_found(kind: str, name: str) -> ApiError:
    return ApiError(404, "NotFound", f'{kind} "{name}" not found')


def already_exists(kind: str, name: str) -> ApiError:
    return ApiError(409, "AlreadyExists", f'{kind} "{name}" already exists')


def conflict(kind: str, name: str, message: str = "") -> ApiError:
    return ApiError(409, "Conflict", message or f'operation on {kind} "{name}" conflicted')


def expired(kind: str, message: str = "") -> ApiError:
    """410 Gone — the requested watch resourceVersion predates the
    server's retained event window (etcd compaction / watch cache
    horizon); the only recovery is a fresh list."""
    return ApiError(410, "Expired",
                    message or f"too old resource version for {kind}")


def is_expired(err: BaseException) -> bool:
    return isinstance(err, ApiError) and err.code == 410


def is_not_found(err: BaseException) -> bool:
    """ref: k8sutil.go:80-82 IsKubernetesResourceNotFoundError."""
    return isinstance(err, ApiError) and err.code == 404 and err.reason != "Conflict"


def is_already_exists(err: BaseException) -> bool:
    """ref: k8sutil.go:76-78 IsKubernetesResourceAlreadyExistError."""
    return isinstance(err, ApiError) and err.code == 409 and err.reason == "AlreadyExists"


def is_conflict(err: BaseException) -> bool:
    return isinstance(err, ApiError) and err.code == 409 and err.reason == "Conflict"
