"""Rate-limited work queue.

Reference parity: the controller's workqueue
(pkg/controller/controller.go:60-63,105): client-go's
``workqueue.NewRateLimitingQueue`` with per-item exponential backoff — base
10 s, cap 360 s (controller.go:60-63; BASELINE.md "workqueue backoff").

Semantics preserved from client-go because the controller's correctness
depends on them:
- an item present in the queue is never duplicated (dirty-set dedup);
- an item being processed that is re-added is re-queued after ``done``
  (processing-set), so no two workers ever reconcile the same job
  concurrently;
- ``add_rate_limited`` applies per-item exponential backoff;
- ``forget`` resets the item's failure count.

Observability mirrors client-go's workqueue metrics provider: with a
``metrics`` registry attached (controller/statusserver.Metrics), the queue
counts adds and retries, and observes queue latency (add → get, which
includes any backoff delay) and work duration (get → done) into fixed-bucket
histograms. The depth / unfinished-work / longest-running gauges are sampled
at scrape time via ``__len__``/``unfinished_work_seconds``/
``longest_running_processor_seconds``.

The clock is injectable for tests (the reference's tests never covered its
queue; these do), and the metrics observations derive purely from it — so
histogram tests are deterministic.
"""

from __future__ import annotations

import heapq
import time
from typing import Any, Callable, Dict, List, Optional, Set
from tpu_operator.util import lockdep

DEFAULT_BASE_DELAY = 10.0   # seconds (ref: controller.go:61)
DEFAULT_MAX_DELAY = 360.0   # seconds (ref: controller.go:62)


class RateLimitingQueue:
    def __init__(
        self,
        base_delay: float = DEFAULT_BASE_DELAY,
        max_delay: float = DEFAULT_MAX_DELAY,
        clock: Callable[[], float] = time.monotonic,
        metrics: Optional[Any] = None,
    ):
        self._base = base_delay
        self._max = max_delay
        self._clock = clock
        self._metrics = metrics
        self._cond = lockdep.condition("RateLimitingQueue._cond")
        self._queue: List[Any] = []  # guarded-by: _cond
        self._dirty: Set[Any] = set()  # guarded-by: _cond
        self._processing: Set[Any] = set()  # guarded-by: _cond
        self._failures: Dict[Any, int] = {}  # guarded-by: _cond
        self._delayed: List[tuple] = []  # heap of (ready_at, seq, item); guarded-by: _cond
        self._seq = 0
        self._shutdown = False  # guarded-by: _cond
        # telemetry state: when items entered the queue / started processing
        self._added_at: Dict[Any, float] = {}  # guarded-by: _cond
        self._processing_since: Dict[Any, float] = {}  # guarded-by: _cond

    # -- core queue -----------------------------------------------------------

    def _enqueue_locked(self, item: Any) -> None:
        self._queue.append(item)
        self._added_at.setdefault(item, self._clock())

    def add(self, item: Any) -> None:
        with self._cond:
            if self._shutdown or item in self._dirty:
                return
            if self._metrics is not None:
                self._metrics.inc("workqueue_adds_total")
            self._dirty.add(item)
            if item in self._processing:
                return  # will be re-queued on done()
            self._enqueue_locked(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Blocks until an item is available (moving due delayed items in),
        the timeout elapses, or the queue is shut down. Returns None on
        timeout/shutdown."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                self._drain_delayed_locked()
                if self._queue:
                    item = self._queue.pop(0)
                    self._processing.add(item)
                    self._dirty.discard(item)
                    now = self._clock()
                    added = self._added_at.pop(item, None)
                    if self._metrics is not None and added is not None:
                        self._metrics.observe(
                            "workqueue_queue_duration_seconds", now - added)
                    self._processing_since[item] = now
                    return item
                if self._shutdown:
                    return None
                now = self._clock()
                if deadline is not None and now >= deadline:
                    return None  # timeout — never conflated with a due item
                waits = []
                if self._delayed:
                    waits.append(self._delayed[0][0] - now)
                if deadline is not None:
                    waits.append(deadline - now)
                wait = min(waits) if waits else None
                if wait is not None and wait <= 0:
                    continue  # a delayed item became due; loop re-drains it
                # No timeout and nothing pending: block on the condition
                # (add/add_after/shutdown notify) instead of polling.
                self._cond.wait(wait)

    def done(self, item: Any) -> None:
        with self._cond:
            self._processing.discard(item)
            since = self._processing_since.pop(item, None)
            if self._metrics is not None and since is not None:
                self._metrics.observe("workqueue_work_duration_seconds",
                                      self._clock() - since)
            if item in self._dirty:
                self._enqueue_locked(item)
                self._cond.notify()

    # -- rate limiting --------------------------------------------------------

    def num_requeues(self, item: Any) -> int:
        with self._cond:
            return self._failures.get(item, 0)

    def add_rate_limited(self, item: Any) -> None:
        """Re-queue after exponential per-item backoff
        (ref: AddRateLimited at controller.go:200)."""
        with self._cond:
            if self._shutdown:
                return
            if self._metrics is not None:
                self._metrics.inc("workqueue_retries_total")
            failures = self._failures.get(item, 0)
            delay = min(self._base * (2 ** failures), self._max)
            self._failures[item] = failures + 1
            self._seq += 1
            # Latency is measured from *scheduling*, so the backoff delay
            # shows up in workqueue_queue_duration_seconds — that is the
            # "how long did the job sit queued?" number.
            self._added_at.setdefault(item, self._clock())
            heapq.heappush(self._delayed, (self._clock() + delay, self._seq, item))
            self._cond.notify()

    def add_after(self, item: Any, delay: float, timer: bool = False) -> None:
        """Delayed enqueue. ``timer=True`` marks a scheduled wakeup (the
        deadline manager's exact-time obligations) rather than an error
        requeue: it is excluded from ``workqueue_retries_total``, and its
        queue latency is measured from when the item becomes *due* (stamped
        at drain time) instead of from scheduling — a TTL wakeup parked for
        a day must not land a 86400 s sample in the queue-duration
        histogram that exists to answer "how long did work wait?"."""
        with self._cond:
            if self._shutdown:
                return
            if not timer:
                if self._metrics is not None:
                    self._metrics.inc("workqueue_retries_total")
                self._added_at.setdefault(item, self._clock())
            self._seq += 1
            heapq.heappush(self._delayed, (self._clock() + delay, self._seq, item))
            self._cond.notify()

    def forget(self, item: Any) -> None:
        """Reset backoff state (ref: Forget at controller.go:261-265)."""
        with self._cond:
            self._failures.pop(item, None)

    # -- lifecycle ------------------------------------------------------------

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    @property
    def is_shutdown(self) -> bool:
        with self._cond:
            return self._shutdown

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    # -- telemetry gauges (sampled at /metrics scrape) -------------------------

    def unfinished_work_seconds(self) -> float:
        """Seconds of in-flight processing not yet marked done, summed over
        workers (client-go: UnfinishedWorkSeconds)."""
        with self._cond:
            now = self._clock()
            return sum(now - t for t in self._processing_since.values())

    def longest_running_processor_seconds(self) -> float:
        """Age of the oldest in-flight item (client-go:
        LongestRunningProcessorSeconds); 0 when idle."""
        with self._cond:
            if not self._processing_since:
                return 0.0
            return self._clock() - min(self._processing_since.values())

    # -- internals (call with lock held) --------------------------------------

    def _drain_delayed_locked(self) -> None:
        now = self._clock()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, item = heapq.heappop(self._delayed)
            if item in self._dirty:
                continue
            self._dirty.add(item)
            if item not in self._processing:
                self._enqueue_locked(item)
