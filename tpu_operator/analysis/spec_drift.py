"""Rule ``spec-drift``: the five hand-maintained spec artifacts agree.

A TPUJob spec field exists in five places: the ``types.py`` dataclass wire
format (``from_dict``), the ``schema.py`` structural schema, ``defaults.py``,
``validation.py``, and the generated CRD YAML (examples + chart). The
reference generated most of this; we hand-edit it, so this rule makes the
cross-file contract machine-checked:

- every wire key parsed by ``TPUJobSpec.from_dict`` / ``TPUReplicaSpec
  .from_dict`` appears in ``spec_schema()`` / ``replica_spec_schema()``
  (and vice versa — a schema key with no dataclass backing is also drift);
- every wire key's snake_case attribute is mentioned by ``defaults.py`` and
  ``validation.py``, or carries an explicit allowlist entry documenting why
  it needs no defaulting/validation;
- ``hack/gen_crd.py --check`` passes (the CRD YAML on disk is byte-identical
  to what the schema renders).

Keys: ``schema:<key>``, ``types:<key>``, ``defaults:<key>``,
``validation:<key>``, ``crd:drift``.
"""

from __future__ import annotations

import ast
import re
import subprocess
import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from tpu_operator.analysis.base import Finding, parse_file, rel, str_const, \
    camel_to_snake

RULE = "spec-drift"

TYPES = "tpu_operator/apis/tpujob/v1alpha1/types.py"
SCHEMA = "tpu_operator/apis/tpujob/v1alpha1/schema.py"
DEFAULTS = "tpu_operator/apis/tpujob/v1alpha1/defaults.py"
VALIDATION = "tpu_operator/apis/tpujob/validation.py"

# (dataclass in types.py, schema builder in schema.py)
PAIRS = (
    ("TPUJobSpec", "spec_schema"),
    ("TPUReplicaSpec", "replica_spec_schema"),
)

_WIRE_KEY_RE = re.compile(r"^[a-z][a-zA-Z0-9]*$")


def _from_dict_keys(tree: ast.Module, cls_name: str) -> Dict[str, int]:
    """Wire keys consumed by ``<cls>.from_dict``: string literals used as
    ``d.get(...)`` args, ``d[...]`` subscripts, ``"k" in d`` membership
    tests, or first args of helpers defined inside from_dict (the
    ``opt_int("activeDeadlineSeconds")`` pattern)."""
    fn: Optional[ast.FunctionDef] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            for item in node.body:
                if isinstance(item, ast.FunctionDef) \
                        and item.name == "from_dict":
                    fn = item
    if fn is None:
        return {}
    local_helpers = {n.name for n in ast.walk(fn)
                     if isinstance(n, ast.FunctionDef) and n is not fn}
    keys: Dict[str, int] = {}

    def record(node: ast.AST) -> None:
        value = str_const(node)
        if value is not None and _WIRE_KEY_RE.match(value):
            keys.setdefault(value, node.lineno)

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            is_get = (isinstance(node.func, ast.Attribute)
                      and node.func.attr == "get")
            is_helper = (isinstance(node.func, ast.Name)
                         and node.func.id in local_helpers)
            if (is_get or is_helper) and node.args:
                record(node.args[0])
        elif isinstance(node, ast.Subscript):
            record(node.slice)
        elif isinstance(node, ast.Compare):
            if any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
                record(node.left)
    return keys


def _schema_keys(tree: ast.Module, fn_name: str) -> Dict[str, int]:
    """Top-level property keys of the ``_obj({...})`` a schema builder
    returns."""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == fn_name:
            for stmt in ast.walk(node):
                if isinstance(stmt, ast.Return) \
                        and isinstance(stmt.value, ast.Call) \
                        and isinstance(stmt.value.func, ast.Name) \
                        and stmt.value.func.id == "_obj" \
                        and stmt.value.args \
                        and isinstance(stmt.value.args[0], ast.Dict):
                    out: Dict[str, int] = {}
                    for k in stmt.value.args[0].keys:
                        value = str_const(k) if k is not None else None
                        if value is not None:
                            out.setdefault(value, k.lineno)
                    return out
    return {}


def _mention_lines(path: Path) -> Tuple[str, bool]:
    try:
        return path.read_text(encoding="utf-8"), True
    except OSError:
        return "", False


def run(root: Path) -> List[Finding]:
    findings: List[Finding] = []
    types_path = root / TYPES
    schema_path = root / SCHEMA
    types_tree = parse_file(types_path)
    schema_tree = parse_file(schema_path)
    if types_tree is None or schema_tree is None:
        return findings  # nothing to check in this tree

    defaults_src, have_defaults = _mention_lines(root / DEFAULTS)
    validation_src, have_validation = _mention_lines(root / VALIDATION)

    for cls_name, schema_fn in PAIRS:
        wire = _from_dict_keys(types_tree, cls_name)
        schema = _schema_keys(schema_tree, schema_fn)
        if not wire or not schema:
            continue
        for key, line in sorted(wire.items()):
            if key not in schema:
                findings.append(Finding(
                    RULE, rel(root, types_path), line,
                    f"{cls_name} wire key {key!r} is missing from "
                    f"schema.{schema_fn}() — the strict schema would "
                    f"reject (or a pruning apiserver silently drop) it",
                    key=f"schema:{key}"))
            snake = camel_to_snake(key)
            for src, ok, label in (
                    (defaults_src, have_defaults, "defaults"),
                    (validation_src, have_validation, "validation")):
                if not ok:
                    continue
                if not re.search(rf"\b{re.escape(snake)}\b", src):
                    findings.append(Finding(
                        RULE, rel(root, types_path), line,
                        f"{cls_name} field {key!r} ({snake}) is handled by "
                        f"neither {label}.py nor an allowlist entry "
                        f"documenting why it needs no {label}",
                        key=f"{label}:{key}"))
        for key, line in sorted(schema.items()):
            if key not in wire:
                findings.append(Finding(
                    RULE, rel(root, schema_path), line,
                    f"schema.{schema_fn}() property {key!r} has no "
                    f"backing wire key in {cls_name}.from_dict — the "
                    f"apiserver accepts a field the operator ignores",
                    key=f"types:{key}"))

    gen_crd = root / "hack" / "gen_crd.py"
    if gen_crd.is_file():
        proc = subprocess.run(
            [sys.executable, str(gen_crd), "--check"],
            cwd=root, capture_output=True, text=True)
        if proc.returncode != 0:
            output = (proc.stdout or proc.stderr).strip()
            first_line = output.splitlines()[0] if output else \
                f"exit {proc.returncode}, no output"
            findings.append(Finding(
                RULE, rel(root, gen_crd), 1,
                "generated CRD YAML drifted from schema.py — run "
                f"`python hack/gen_crd.py` ({first_line})",
                key="crd:drift"))
    return findings
