"""Rule ``payload-image``: shipped imports resolve from pinned requirements.

Folded in from the former standalone ``hack/check_payload_image.py`` so all
contract checks share one runner, finding format, and allowlist (the shim
at hack/check_payload_image.py now delegates here). Three tiers:

1. Static: every top-level import reachable from each image's module set is
   stdlib, in-repo, or provided by that image's requirements.txt.
2. Lockstep: the pyproject ``payload`` extra matches the payload image's
   requirements.txt pin-for-pin.
3. Dynamic (live repo only): every payload module actually imports in the
   dev environment, so a broken module body fails CI rather than job
   startup.

Keys: ``import:<file>:<module>``, ``pin-drift:<name>``,
``module-import:<module>``.
"""

from __future__ import annotations

import ast
import importlib
import re
import sys
from pathlib import Path
from typing import Dict, List, Set

from tpu_operator.analysis.base import Finding, parse_file, rel

RULE = "payload-image"

# requirement-name -> import names it provides. Keep in lockstep with
# build/images/*/requirements.txt.
REQUIREMENT_PROVIDES = {
    "jax": {"jax", "jaxlib"},
    "flax": {"flax"},
    "optax": {"optax"},
    "orbax-checkpoint": {"orbax"},
    "numpy": {"numpy"},
    "pyyaml": {"yaml"},
}


def parse_requirements(path: Path) -> Set[str]:
    provided: Set[str] = set()
    for line in path.read_text(encoding="utf-8").splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        name = re.split(r"[\[=<>!~;]", line, 1)[0].strip().lower()
        provided |= REQUIREMENT_PROVIDES.get(name, {name.replace("-", "_")})
    return provided


def _module_imports(path: Path) -> Dict[str, int]:
    tree = parse_file(path)
    if tree is None:
        # Unparseable file: the dynamic import tier (live repo) reports it
        # as a module-import finding; a seeded-bad fixture file must not
        # crash the whole analysis run.
        return {}
    tops: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                tops.setdefault(alias.name.split(".")[0], node.lineno)
        elif isinstance(node, ast.ImportFrom) and node.level == 0 \
                and node.module:
            tops.setdefault(node.module.split(".")[0], node.lineno)
    return tops


def _check_image(root: Path, label: str, files: List[Path],
                 reqs: Path) -> List[Finding]:
    if not reqs.is_file():
        return []
    provided = parse_requirements(reqs)
    findings = []
    for f in sorted(files):
        for top, line in sorted(_module_imports(f).items()):
            if top in sys.stdlib_module_names or top == "tpu_operator":
                continue
            if top in provided:
                continue
            findings.append(Finding(
                RULE, rel(root, f), line,
                f"{label}: imports {top!r} which {reqs.name} does not "
                f"install — explodes at job startup, not build time",
                key=f"import:{rel(root, f)}:{top}"))
    return findings


def _pins(lines: List[str]) -> Dict[str, str]:
    out = {}
    for line in lines:
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        name = re.split(r"[\[=<>!~;]", line, 1)[0].strip().lower()
        ver = line.split("==", 1)[1].strip() if "==" in line else ""
        out[name.replace("-", "_")] = ver
    return out


def _payload_extra_lines(pyproject: Path) -> List[str]:
    """The pyproject ``payload`` extra, via tomllib when available (3.11+)
    with a regex fallback for older interpreters."""
    try:
        import tomllib
        with open(pyproject, "rb") as f:
            proj = tomllib.load(f)
        return list(proj["project"]["optional-dependencies"]["payload"])
    except ImportError:
        # Non-greedy up to a closing bracket at column 0 — a `]` inside an
        # extras marker ("jax[tpu]==...") must not end the capture.
        m = re.search(r"^payload\s*=\s*\[(.*?)^\]",
                      pyproject.read_text(encoding="utf-8"),
                      re.DOTALL | re.MULTILINE)
        if not m:
            return []
        return [part.strip().strip("\"'")
                for part in m.group(1).split(",") if part.strip()]
    except KeyError:
        return []


def _check_lockstep(root: Path) -> List[Finding]:
    """pyproject 'payload' extra ↔ payload image requirements.txt."""
    pyproject = root / "pyproject.toml"
    req_path = root / "build/images/tpu_payload/requirements.txt"
    if not pyproject.is_file() or not req_path.is_file():
        return []
    extra_lines = _payload_extra_lines(pyproject)
    if not extra_lines:
        return []
    img = _pins(req_path.read_text(encoding="utf-8").splitlines())
    extra = _pins(extra_lines)
    findings = []
    for name, ver in sorted(extra.items()):
        if img.get(name) != ver:
            findings.append(Finding(
                RULE, rel(root, pyproject), 1,
                f"pin drift: pyproject payload extra has {name}=={ver} but "
                f"the payload image requirements.txt has "
                f"{img.get(name, 'nothing')}", key=f"pin-drift:{name}"))
    for name, ver in sorted(img.items()):
        if name not in extra:
            findings.append(Finding(
                RULE, rel(root, req_path), 1,
                f"pin drift: payload image requirements.txt has "
                f"{name}=={ver} but the pyproject payload extra omits it",
                key=f"pin-drift:{name}"))
    return findings


def _check_dynamic(root: Path, payload_files: List[Path]) -> List[Finding]:
    findings = []
    for f in sorted(payload_files):
        mod = "tpu_operator.payload." + f.stem if f.stem != "__init__" \
            else "tpu_operator.payload"
        try:
            importlib.import_module(mod)
        except Exception as exc:  # noqa: BLE001 — report all import failures
            findings.append(Finding(
                RULE, rel(root, f), 1,
                f"import {mod}: {type(exc).__name__}: {exc}",
                key=f"module-import:{mod}"))
    return findings


def run(root: Path) -> List[Finding]:
    pkg = root / "tpu_operator"
    if not pkg.is_dir():
        return []
    payload_files = sorted((pkg / "payload").glob("*.py"))
    # The analysis package is CI tooling: it ships in the sdist but the
    # operator binary never imports it, so its (gated) dev-only imports
    # don't bind the image requirements.
    operator_files = [
        f for f in sorted(pkg.rglob("*.py"))
        if "payload" not in f.parts and "analysis" not in f.parts
        and "__pycache__" not in f.parts
    ]
    findings = _check_image(
        root, "payload-image", payload_files,
        root / "build/images/tpu_payload/requirements.txt")
    findings += _check_image(
        root, "operator-image", operator_files,
        root / "build/images/tpu_operator/requirements.txt")
    findings += _check_lockstep(root)
    # Dynamic tier only against the live repo (importing fixture-tree
    # modules under the installed package name would be nonsense).
    if (root / "tpu_operator/analysis/payload_image.py").resolve() \
            == Path(__file__).resolve():
        findings += _check_dynamic(root, payload_files)
    return findings
