"""Rule ``concurrency``: lock discipline in the threaded control plane.

Three checks over the informer/workqueue/controller/checkpoint layer (the
code that actually runs multi-threaded: reflector threads, reconcile
workers, HTTP handler threads, the checkpoint verify worker):

1. **guarded-by annotations.** A shared mutable attribute declares its lock
   at its ``__init__`` assignment::

       self._items: Dict[str, Any] = {}  # guarded-by: _lock

   Every other access to ``self._items`` inside the class must then sit
   lexically inside ``with self._lock:`` — or in a method whose name ends
   in ``_locked`` (the existing call-with-lock-held convention). This is
   Java's @GuardedBy, AST-flavored: annotations are cheap to write and the
   checker catches the access someone adds in review without the lock.

2. **Threads started but never joined.** A ``threading.Thread`` that is
   neither ``daemon=True`` nor ``.join()``-ed in the same file leaks a
   non-daemon thread that can hang interpreter shutdown.

3. **Blocking calls under a lock.** Inside a ``with <lock>:`` block
   (anything lock/cond-shaped), calls to ``time.sleep``/``sleep``,
   ``subprocess.*``, ``socket.*``, ``urlopen``, or a clientset RPC
   (``*.clientset.*``) are flagged — they serialize every other thread on
   the lock behind I/O. Calls on the lock object itself (``cond.wait``)
   are exempt: they release it.

Keys: ``guarded-by:<file>:<Class>.<attr>:<method>``,
``thread:<file>:<func>``, ``lock-blocking:<file>:<func>:<callee>``.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional

from tpu_operator.analysis.base import Finding, ancestors, attach_parents, \
    comment_annotations, dotted_name, iter_py_files, parse_file, rel, \
    self_attr

RULE = "concurrency"

# The threaded control-plane surface this rule watches — shared with the
# lock-order and escape rules, so all three see one universe.
SCAN = (
    ("tpu_operator", "client"),
    ("tpu_operator", "controller"),
    ("tpu_operator", "obs"),
    ("tpu_operator", "scheduler"),
    ("tpu_operator", "store"),
    ("tpu_operator", "trainer"),
    ("tpu_operator", "util"),
    ("tpu_operator", "testing", "cluster.py"),
    ("tpu_operator", "payload", "autotune.py"),
    ("tpu_operator", "payload", "checkpoint.py"),
    ("tpu_operator", "payload", "kvcache.py"),
    ("tpu_operator", "payload", "serve.py"),
    ("tpu_operator", "payload", "startup.py"),
    ("tpu_operator", "payload", "steptrace.py"),
    ("tpu_operator", "payload", "train.py"),
    ("tpu_operator", "payload", "warmstore.py"),
)

_BLOCKING_ATTRS = {"sleep", "_sleep", "urlopen", "getaddrinfo",
                   "create_connection", "check_call", "check_output"}
_BLOCKING_PREFIXES = ("subprocess.", "socket.", "requests.")


def _lockish(expr: ast.AST) -> Optional[str]:
    """Dotted name of a with-item that looks like a lock acquisition."""
    name = dotted_name(expr)
    leaf = name.rsplit(".", 1)[-1].lower()
    if "lock" in leaf or "cond" in leaf or "mutex" in leaf:
        return name
    return None


def _enclosing_with_locks(node: ast.AST) -> List[str]:
    """Dotted names of every lock-shaped ``with`` the node sits inside."""
    locks: List[str] = []
    for anc in ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                lock = _lockish(item.context_expr)
                if lock:
                    locks.append(lock)
    return locks


def _method_of(node: ast.AST) -> Optional[ast.FunctionDef]:
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc  # nearest function
    return None


def _check_guarded(tree: ast.Module, path_rel: str,
                   notes: Dict[int, str]) -> List[Finding]:
    findings: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        init = next((m for m in cls.body if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        if init is None:
            continue
        guarded: Dict[str, str] = {}
        for stmt in ast.walk(init):
            target = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = stmt.targets[0]
            elif isinstance(stmt, ast.AnnAssign):
                target = stmt.target
            attr = self_attr(target) if target is not None else None
            # A multi-line assignment can carry the annotation on any of
            # its physical lines (black-wrapped dict literals put the
            # comment on the continuation line).
            lock = None
            if hasattr(stmt, "lineno"):
                end = getattr(stmt, "end_lineno", None) or stmt.lineno
                for line in range(stmt.lineno, end + 1):
                    lock = notes.get(line)
                    if lock:
                        break
            if attr and lock:
                guarded[attr] = lock.removeprefix("self.")
        if not guarded:
            continue
        for method in cls.body:
            if not isinstance(method, ast.FunctionDef) \
                    or method.name == "__init__" \
                    or method.name.endswith("_locked"):
                continue
            for node in ast.walk(method):
                attr = self_attr(node)
                if attr is None or attr not in guarded:
                    continue
                # Only accesses from *this* method frame count; a nested
                # function/class (HTTP handler closures) has its own rules.
                if _method_of(node) is not method:
                    continue
                lock = guarded[attr]
                held = {h.removeprefix("self.")
                        for h in _enclosing_with_locks(node)}
                if lock not in held:
                    findings.append(Finding(
                        RULE, path_rel, node.lineno,
                        f"{cls.name}.{attr} is guarded-by {lock} but "
                        f"{method.name}() accesses it outside "
                        f"`with self.{lock}:` (rename the method *_locked "
                        f"if the caller holds it)",
                        key=f"guarded-by:{path_rel}:{cls.name}.{attr}:"
                            f"{method.name}"))
    return findings


def _target_leaf(node: ast.AST) -> Optional[str]:
    """Leaf name a value is bound to (``t`` or ``self._worker``), walking
    up through the immediate Assign/AnnAssign parent."""
    parent = getattr(node, "parent", None)
    target = None
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        target = parent.targets[0]
    elif isinstance(parent, ast.AnnAssign):
        target = parent.target
    if isinstance(target, ast.Name):
        return target.id
    leaf = self_attr(target) if target is not None else None
    return leaf


def _check_threads(tree: ast.Module, path_rel: str) -> List[Finding]:
    findings: List[Finding] = []
    # Receiver leaf names something calls .join() on — matched against the
    # Thread's binding name, NOT a whole-file substring test (which
    # ','.join / os.path.join would satisfy vacuously).
    joined: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join":
            recv = node.func.value
            if isinstance(recv, ast.Name):
                joined.add(recv.id)
            else:
                leaf = self_attr(recv)
                if leaf:
                    joined.add(leaf)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name not in ("threading.Thread", "Thread"):
            continue
        daemon = next((kw.value for kw in node.keywords
                       if kw.arg == "daemon"), None)
        if isinstance(daemon, ast.Constant) and daemon.value is True:
            continue
        bound = _target_leaf(node)
        if bound is not None and bound in joined:
            continue
        fn = _method_of(node)
        fn_name = fn.name if fn is not None else "<module>"
        findings.append(Finding(
            RULE, path_rel, node.lineno,
            f"thread created in {fn_name}() is neither daemon=True nor "
            f"joined (no .join() on its binding in this file) — it can "
            f"hang interpreter shutdown",
            key=f"thread:{path_rel}:{fn_name}"))
    return findings


def _check_blocking(tree: ast.Module, path_rel: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        held = _enclosing_with_locks(node)
        if not held:
            continue
        callee = dotted_name(node.func)
        leaf = callee.rsplit(".", 1)[-1]
        blocking = (
            leaf in _BLOCKING_ATTRS
            or callee == "time.sleep"
            or any(callee.startswith(p) for p in _BLOCKING_PREFIXES)
            or ".clientset." in f".{callee}."
        )
        if not blocking:
            continue
        # Calls on the lock object itself release it (cond.wait/notify).
        if any(callee.startswith(f"{lock}.") for lock in held):
            continue
        fn = _method_of(node)
        fn_name = fn.name if fn is not None else "<module>"
        findings.append(Finding(
            RULE, path_rel, node.lineno,
            f"blocking call {callee}() inside `with {held[0]}:` — every "
            f"thread contending on the lock serializes behind this I/O",
            key=f"lock-blocking:{path_rel}:{fn_name}:{callee}"))
    return findings


def run(root: Path) -> List[Finding]:
    findings: List[Finding] = []
    seen = set()
    for parts in SCAN:
        for path in iter_py_files(root, *parts):
            if path in seen:
                continue
            seen.add(path)
            tree = parse_file(path)
            if tree is None:
                continue
            attach_parents(tree)
            path_rel = rel(root, path)
            notes = comment_annotations(path, "guarded-by")
            findings += _check_guarded(tree, path_rel, notes)
            findings += _check_threads(tree, path_rel)
            findings += _check_blocking(tree, path_rel)
    return findings
