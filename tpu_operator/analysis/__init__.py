"""Static-analysis suite for the operator's hand-maintained contracts.

The reference tf-operator kept its API artifacts consistent with ~1,770 LoC
of generated client plumbing (SURVEY.md §0); this reproduction hand-edits
five artifacts per spec field plus two runtime contracts (env injection and
the heartbeat body). Kubernetes-operator practice says those contracts
should be machine-checked, not reviewer-checked — this package is that
machine check, stdlib-only so it runs anywhere the control plane does.

Rules (each a module exporting ``run(root) -> List[Finding]``):

- ``spec_drift``       — types.py ⊆ schema.py/defaults.py/validation.py
                         and the generated CRDs are byte-identical.
- ``env_contract``     — injected env vars are read by the payload and
                         payload env reads are injected (or allowlisted).
- ``status_contract``  — heartbeat keys posted ⊆ sanitized ⊆ status schema;
                         metric names are documented and tested.
- ``concurrency``      — ``# guarded-by:`` lock annotations, threads that
                         are never joined, blocking calls under a lock.
- ``exception_policy`` — no broad/silent excepts on controller paths;
                         retryable exit codes only via named constants.
- ``payload_image``    — every import shipped in an image resolves from its
                         pinned requirements (folded in from the former
                         hack/check_payload_image.py).

``driver.run_analysis`` runs them all against one root with one allowlist
(hack/analyze_allowlist.txt); ``hack/analyze.py`` is the CLI, gated in
hack/verify.sh.
"""

from tpu_operator.analysis.base import Allowlist, Finding  # noqa: F401
from tpu_operator.analysis.driver import RULES, run_analysis  # noqa: F401
