"""Rule ``lifecycle``: per-job state must die with the job.

The operator's most recurring bug class is per-job state that outlives
the job — leaked event-dedup entries (PR 1), unbounded queue-depth label
series (PR 7), metric series only pruned after a PR 9 hand-audit. This
rule makes the ownership a checked contract instead of reviewer
folklore, in the ``# guarded-by:`` style:

1. **Mandatory ``# per-job:`` annotations.** A container attribute keyed
   by job identity declares its removal path at its ``__init__``
   assignment::

       self._scheduled: Dict[str, float] = joblife.track(
           "DeadlineManager._scheduled")  # per-job: forget

   "Keyed by job identity" is detected from the class's own accesses: a
   subscript/``get``/``pop``/``setdefault``/``add``/``discard``/``in``
   whose key expression is a ``key``/``uid`` name (or attribute) or a
   ``(namespace, name)``-shaped tuple. An unannotated per-job-shaped
   container is a finding — someone added job-keyed state with no
   declared teardown.

2. **The declared removers must really remove, and really run.** Each
   method named in the annotation must exist in the same class and
   contain a removal operation on the attribute (``.pop``/``.popitem``/
   ``.clear``/``.discard``/``.remove``/``del``/reassignment), and must
   be referenced from somewhere in the scanned tree — a remover nobody
   calls is a leak with paperwork.

3. **Annotated containers register with the runtime witness.** The
   assignment must construct through ``joblife.track("Class._attr")``
   (name matching the annotation site exactly) so the ``TPUJOB_JOBLIFE``
   deletion sweep sees it; a deliberate opt-out says ``no-track`` in the
   annotation (e.g. state whose entries are transient per-operation,
   not per-lifetime).

4. **Job-identity metric families prune on deletion.** Any
   ``inc``/``set_gauge``/``observe`` whose ``labels`` literal carries
   both ``namespace`` and ``name`` names a family whose series are
   per-job state in the metrics registry; the rule fails unless some
   ``Metrics.remove_series`` call site names the same family (the
   controller's deletion path owns these today). Family names written
   through variables resolve against string literals in the enclosing
   function intersected with the registered-family set (parsed from
   ``Metrics.register`` calls), which covers the tuple-driven fold/prune
   loops.

Keys: ``per-job:<file>:<Class>.<attr>`` (missing annotation),
``per-job-remover:<file>:<Class>.<attr>:<method>`` (remover missing or
removal-free), ``per-job-unreached:<file>:<Class>.<attr>:<method>``
(remover never referenced), ``per-job-untracked:<file>:<Class>.<attr>``
(no/wrong ``joblife.track``), ``per-job-metric:<family>`` (no
``remove_series`` site).

Scope: the long-lived control-plane surface — controller (incl. the
status server), scheduler, trainer, store, util. The client layer's
generic cache machinery (informer stores, workqueues) keys on opaque
items and is owned by the watch protocol itself; it stays out of scope
here, covered by the concurrency/escape rules.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from pathlib import Path
from typing import Any, Dict, List, Optional, Set, Tuple

from tpu_operator.analysis.base import Finding, attach_parents, dotted_name, \
    enclosing_function, iter_py_files, parse_file, rel, self_attr, str_const

RULE = "lifecycle"

# The long-lived control-plane surface whose containers outlive any one
# job (per-job objects like TrainingJob/GangRuntime die with their map
# entry; their internals are covered transitively by the entry's sweep).
SCAN = (
    ("tpu_operator", "controller"),
    ("tpu_operator", "obs"),
    ("tpu_operator", "scheduler"),
    ("tpu_operator", "trainer"),
    ("tpu_operator", "store"),
    ("tpu_operator", "util"),
    # The fake-cluster harness runs threaded against the same stores the
    # operator watches; its containers (pod sims, kubelets, timers) must
    # prove the same no-residue discipline the control plane does.
    ("tpu_operator", "testing", "cluster.py"),
)

# Names whose appearance as a container key mark it per-job-keyed.
JOB_KEY_NAMES = {"key", "job_key", "jobkey", "uid", "job_uid"}
JOB_KEY_ATTRS = {"key", "uid"}
NS_NAMES = {"namespace", "ns"}
NAME_NAMES = {"name"}

_KEYED_METHODS = {"get", "pop", "setdefault", "add", "discard", "remove"}
_REMOVAL_METHODS = {"pop", "popitem", "clear", "discard", "remove"}

# Removers are a comma-joined list (no spaces); the only flag word is
# no-track. Anything after — another tag like guarded-by:, prose — is
# outside the capture, so tags can share a comment line.
_ANNOTATION_RE = re.compile(r"per-job:\s*([A-Za-z0-9_,]+)((?:\s+no-track)?)")


def _per_job_annotations(path: Path) -> Dict[int, Tuple[List[str], Set[str]]]:
    """line -> ([removers], {flags}) for ``# per-job: a,b [no-track]``
    comments (ast drops comments; this walks the token stream)."""
    out: Dict[int, Tuple[List[str], Set[str]]] = {}
    try:
        tokens = tokenize.generate_tokens(
            io.StringIO(path.read_text(encoding="utf-8")).readline)
    except (OSError, tokenize.TokenError, SyntaxError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _ANNOTATION_RE.search(tok.string)
        if not m:
            continue
        removers = [w for w in m.group(1).split(",") if w]
        flags = {"no-track"} if m.group(2).strip() else set()
        out[tok.start[0]] = (removers, flags)
    return out


def _container_value(value: ast.AST) -> Optional[str]:
    """What container an ``__init__`` assignment builds: "dict", "set",
    "track" (a joblife.track call), or None for non-containers."""
    if isinstance(value, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(value, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(value, ast.Call):
        dn = dotted_name(value.func)
        leaf = dn.rsplit(".", 1)[-1]
        if dn.endswith("joblife.track") or dn == "track":
            return "track"
        if leaf in ("dict", "OrderedDict", "defaultdict"):
            return "dict"
        if leaf == "set":
            return "set"
    return None


def _is_job_identity(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Name):
        return expr.id in JOB_KEY_NAMES
    if isinstance(expr, ast.Attribute):
        return expr.attr in JOB_KEY_ATTRS
    if isinstance(expr, ast.Tuple):
        leaves = set()
        for elt in expr.elts:
            if isinstance(elt, ast.Name):
                leaves.add(elt.id)
            elif isinstance(elt, ast.Attribute):
                leaves.add(elt.attr)
        return bool(leaves & NS_NAMES) and bool(leaves & NAME_NAMES)
    return False


def _access_keys(cls: ast.ClassDef, attr: str) -> List[ast.AST]:
    """Key expressions the class uses against ``self.<attr>``."""
    keys: List[ast.AST] = []
    for node in ast.walk(cls):
        if isinstance(node, ast.Subscript) \
                and self_attr(node.value) == attr:
            keys.append(node.slice)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _KEYED_METHODS \
                and self_attr(node.func.value) == attr and node.args:
            keys.append(node.args[0])
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and len(node.comparators) == 1 \
                and self_attr(node.comparators[0]) == attr:
            keys.append(node.left)
    return keys


def _removes_attr(method: ast.FunctionDef, attr: str) -> bool:
    for node in ast.walk(method):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _REMOVAL_METHODS \
                and self_attr(node.func.value) == attr:
            return True
        if isinstance(node, ast.Delete):
            for target in node.targets:
                if isinstance(target, ast.Subscript) \
                        and self_attr(target.value) == attr:
                    return True
        if isinstance(node, ast.Assign) \
                and any(self_attr(t) == attr for t in node.targets):
            return True
    return False


def _reference_index(trees: Dict[str, ast.Module]) -> Set[str]:
    """Every attribute/name referenced anywhere in the scanned tree —
    the (deliberately coarse) reachability oracle for removers."""
    refs: Set[str] = set()
    for tree in trees.values():
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute):
                refs.add(node.attr)
            elif isinstance(node, ast.Name):
                refs.add(node.id)
    return refs


def _check_containers(tree: ast.Module, path_rel: str,
                      notes: Dict[int, Tuple[List[str], Set[str]]],
                      refs: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        init = next((m for m in cls.body if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        if init is None:
            continue
        methods = {m.name: m for m in cls.body
                   if isinstance(m, ast.FunctionDef)}
        for stmt in ast.walk(init):
            target = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = stmt.targets[0]
            elif isinstance(stmt, ast.AnnAssign):
                target = stmt.target
            attr = self_attr(target) if target is not None else None
            if attr is None or stmt.value is None:
                continue
            kind = _container_value(stmt.value)
            if kind is None:
                continue
            # A multi-line assignment can carry the annotation on any of
            # its physical lines (the guarded-by convention).
            note = None
            end = getattr(stmt, "end_lineno", None) or stmt.lineno
            for line in range(stmt.lineno, end + 1):
                note = notes.get(line)
                if note is not None:
                    break
            shaped = any(_is_job_identity(k) for k in _access_keys(cls, attr))
            qual = f"{cls.name}.{attr}"
            if note is None:
                if shaped:
                    findings.append(Finding(
                        RULE, path_rel, stmt.lineno,
                        f"{qual} is keyed by job identity but carries no "
                        f"`# per-job:` annotation — declare its removal "
                        f"path on the delete/terminal/TTL path (or "
                        f"allowlist with justification)",
                        key=f"per-job:{path_rel}:{qual}"))
                continue
            removers, flags = note
            for remover in removers:
                method = methods.get(remover)
                if method is None or not _removes_attr(method, attr):
                    what = ("does not exist in the class"
                            if method is None else
                            "performs no removal on the attribute")
                    findings.append(Finding(
                        RULE, path_rel, stmt.lineno,
                        f"{qual} declares remover {remover}() which "
                        f"{what} — the per-job contract is unenforced",
                        key=f"per-job-remover:{path_rel}:{qual}:{remover}"))
                elif remover not in refs:
                    findings.append(Finding(
                        RULE, path_rel, stmt.lineno,
                        f"{qual}'s declared remover {remover}() is never "
                        f"referenced anywhere in the scanned tree — a "
                        f"removal path nobody calls is a leak with "
                        f"paperwork",
                        key=f"per-job-unreached:{path_rel}:{qual}:{remover}"))
            if not removers:
                findings.append(Finding(
                    RULE, path_rel, stmt.lineno,
                    f"{qual}'s `# per-job:` annotation names no remover",
                    key=f"per-job-remover:{path_rel}:{qual}:<none>"))
            if "no-track" not in flags:
                ok = kind == "track"
                if ok:
                    lit = (str_const(stmt.value.args[0])
                           if isinstance(stmt.value, ast.Call)
                           and stmt.value.args else None)
                    ok = lit == qual
                if not ok:
                    findings.append(Finding(
                        RULE, path_rel, stmt.lineno,
                        f"{qual} is `# per-job:` annotated but not "
                        f"constructed via joblife.track({qual!r}) — the "
                        f"runtime deletion sweep cannot see it (say "
                        f"no-track in the annotation to opt out "
                        f"deliberately)",
                        key=f"per-job-untracked:{path_rel}:{qual}"))
    return findings


# --- metric families ---------------------------------------------------------

_WRITE_METHODS = {"inc", "set_gauge", "observe"}


def _labels_dict(call: ast.Call) -> Optional[ast.Dict]:
    for kw in call.keywords:
        if kw.arg == "labels" and isinstance(kw.value, ast.Dict):
            return kw.value
    return None


def _has_job_labels(call: ast.Call) -> bool:
    labels = _labels_dict(call)
    if labels is None:
        return False
    keys = {str_const(k) for k in labels.keys if k is not None}
    return "namespace" in keys and "name" in keys


def _literal_names(node: ast.AST) -> Set[str]:
    lit = str_const(node)
    if lit is not None:
        return {lit}
    if isinstance(node, ast.IfExp):
        return _literal_names(node.body) | _literal_names(node.orelse)
    return set()


def _function_constants(node: ast.AST, tree: ast.Module) -> Set[str]:
    scope = enclosing_function(node) or tree
    return {n.value for n in ast.walk(scope)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}


def _check_metrics(trees: Dict[str, Tuple[Path, ast.Module]]
                   ) -> List[Finding]:
    registered: Set[str] = set()
    write_sites: List[Tuple[str, ast.Call, ast.Module]] = []
    remove_sites: List[Tuple[ast.Call, ast.Module]] = []
    for path_rel, (_path, tree) in trees.items():
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            leaf = node.func.attr
            if leaf == "register" and len(node.args) >= 2 \
                    and str_const(node.args[1]) in ("counter", "gauge",
                                                    "histogram"):
                name = str_const(node.args[0])
                if name:
                    registered.add(name)
            elif leaf in _WRITE_METHODS and node.args \
                    and _has_job_labels(node):
                write_sites.append((path_rel, node, tree))
            elif leaf == "remove_series" and node.args:
                remove_sites.append((node, tree))

    known = set(registered)
    for _p, call, _t in write_sites:
        known |= _literal_names(call.args[0])
    for call, _t in remove_sites:
        known |= _literal_names(call.args[0])

    def resolve(call: ast.Call, tree: ast.Module) -> Set[str]:
        names = _literal_names(call.args[0])
        if names:
            return names
        # Written through a variable: every known family named in the
        # enclosing function is a candidate (covers tuple-driven loops).
        return _function_constants(call.args[0], tree) & known

    removed: Set[str] = set()
    for call, tree in remove_sites:
        removed |= resolve(call, tree)
    findings: List[Finding] = []
    seen: Set[str] = set()
    for path_rel, call, tree in write_sites:
        for family in sorted(resolve(call, tree)):
            if family in removed or family in seen:
                continue
            seen.add(family)
            findings.append(Finding(
                RULE, path_rel, call.lineno,
                f"metric family {family} carries job identity labels "
                f"{{namespace,name}} but no Metrics.remove_series call "
                f"site prunes it — its series outlive every deleted job",
                key=f"per-job-metric:{family}"))
    return findings


def run(root: Path) -> List[Finding]:
    trees: Dict[str, Tuple[Path, ast.Module]] = {}
    for parts in SCAN:
        for path in iter_py_files(root, *parts):
            path_rel = rel(root, path)
            if path_rel in trees:
                continue
            tree = parse_file(path)
            if tree is None:
                continue
            attach_parents(tree)
            trees[path_rel] = (path, tree)
    refs = _reference_index({p: t for p, (_f, t) in trees.items()})
    findings: List[Finding] = []
    for path_rel, (path, tree) in trees.items():
        notes = _per_job_annotations(path)
        findings += _check_containers(tree, path_rel, notes, refs)
    findings += _check_metrics(trees)
    return findings
