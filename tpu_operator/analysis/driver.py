"""Analysis driver: run every rule against one root with one allowlist.

``run_analysis`` returns (findings, suppressed, stale_allowlist_entries);
``hack/analyze.py`` is the CLI wrapper gated in hack/verify.sh. Exit policy
(enforced by the CLI): any unsuppressed finding fails; a stale allowlist
entry (suppressing nothing) also fails, so suppressions cannot outlive the
code they excuse.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, List, Optional, Set, Tuple

from tpu_operator.analysis import concurrency, env_contract, escape, \
    exception_policy, lifecycle, lock_order, payload_image, spec_drift, \
    status_contract
from tpu_operator.analysis.base import Allowlist, Finding

# Stable rule-id -> module order; findings print grouped in this order.
# ``lifecycle`` runs first: per-job state ownership is the recurring
# leak class, and its findings are the cheapest to act on.
RULES = {
    lifecycle.RULE: lifecycle,
    spec_drift.RULE: spec_drift,
    env_contract.RULE: env_contract,
    status_contract.RULE: status_contract,
    concurrency.RULE: concurrency,
    lock_order.RULE: lock_order,
    escape.RULE: escape,
    exception_policy.RULE: exception_policy,
    payload_image.RULE: payload_image,
}

DEFAULT_ALLOWLIST = "hack/analyze_allowlist.txt"


def run_analysis(
    root: Path,
    rules: Optional[Iterable[str]] = None,
    allowlist_path: Optional[Path] = None,
) -> Tuple[List[Finding], List[Finding], Set[Tuple[str, str]]]:
    """Run ``rules`` (default: all) against ``root``.

    Returns (active findings, allowlist-suppressed findings, stale
    allowlist entries that matched nothing this run — only computed for
    the rules that actually ran).
    """
    root = Path(root).resolve()
    selected = list(rules) if rules is not None else list(RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule(s): {unknown}; "
                         f"available: {sorted(RULES)}")
    allowlist = Allowlist.load(
        allowlist_path if allowlist_path is not None
        else root / DEFAULT_ALLOWLIST)

    all_findings: List[Finding] = []
    for rule_id in RULES:
        if rule_id in selected:
            all_findings.extend(RULES[rule_id].run(root))

    active = [f for f in all_findings if not allowlist.allows(f)]
    suppressed = [f for f in all_findings if allowlist.allows(f)]
    stale = {(rule, key) for rule, key in allowlist.unused(all_findings)
             if rule in selected}
    return active, suppressed, stale
