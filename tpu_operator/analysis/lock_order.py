"""Rule ``lock-order``: the cross-module lock-acquisition graph.

The per-function ``concurrency`` rule checks each access against *a*
lock; this rule checks that locks nest in one consistent global ORDER —
the property whose violation is a deadlock — and that blocking work
never hides one call-hop below a lock:

1. **Lock-order cycles.** Every ``with self._lock:`` (and the nested
   withs and calls lexically inside it) contributes directed edges
   ``held → acquired`` to one repo-wide graph. Calls are resolved
   best-effort through the AST — ``self.m()`` to the same class,
   ``self.attr.m()`` through the attr's inferred class (constructor
   assignments, parameter annotations, ``Dict[str, T]`` container
   reads), ``fn()`` to same-module functions — and each callee's
   *transitive* acquisition set becomes edge targets, so the
   controller→scheduler→metrics chain is visible even though no single
   function spells it out. A cycle in the final graph is a potential
   deadlock: two threads entering it from different arcs stall forever.
   Lock nodes are named per class attribute (``FleetScheduler._lock``)
   — instance-agnostic, like Linux lockdep's lock classes.

2. **Blocking one-or-more call-hops under a lock.** The existing rule
   flags ``time.sleep`` literally inside a ``with``; this one computes,
   per function, whether it may (transitively) sleep, do socket or
   subprocess I/O, or issue a clientset RPC — and flags any call made
   under a held lock into such a function. This is the shape of the
   PR-6 recorder bug (reconcile workers convoyed behind one thread's
   apiserver RPC) one abstraction layer deeper, where the per-function
   rule is structurally blind.

The ``*_locked`` suffix convention composes: a ``_locked`` method's own
body contributes edges from the caller's held lock (it runs under it),
and its acquisitions of OTHER locks are ordinary edges.

Resolution is deliberately conservative: an attr whose class cannot be
inferred contributes no edges (missed edges are possible; false cycles
are not — every edge has a concrete witness site, reported in the
message). Keys: ``cycle:<A->B->...>`` (canonical rotation) and
``blocking-hop:<file>:<qualname>:<callee>``.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from tpu_operator.analysis.base import Finding, ancestors, attach_parents, \
    dotted_name, iter_py_files, parse_file, rel
from tpu_operator.analysis.concurrency import SCAN, _lockish

RULE = "lock-order"

# Lock-constructor call names (both raw threading and the lockdep
# witness factories every operator module now uses).
_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
    "lockdep.lock", "lockdep.rlock", "lockdep.condition",
}

# Direct blocking shapes (mirrors the per-function concurrency rule).
_BLOCKING_ATTRS = {"sleep", "_sleep", "urlopen", "getaddrinfo",
                   "create_connection", "check_call", "check_output"}
_BLOCKING_PREFIXES = ("subprocess.", "socket.", "requests.")

# Dependency-injected attrs typed ``Any`` in the repo's constructors:
# name-based hints recover the edges annotation erasure hides. Each hint
# only applies when the named class actually exists in the scanned set.
_ATTR_NAME_HINTS = {
    "metrics": "Metrics",
    "_metrics": "Metrics",
    "recorder": "EventRecorder",
    "scheduler": "FleetScheduler",
    "writeback": "WritebackLimiter",
}


def _ann_class_names(ann: Optional[ast.AST]) -> List[str]:
    """Candidate class names inside an annotation expression —
    ``Optional[FleetScheduler]`` → ["Optional", "FleetScheduler"];
    string annotations ("FakeClientset") included."""
    if ann is None:
        return []
    names: List[str] = []
    for node in ast.walk(ann):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            names.append(node.value.split("[")[0].strip('"\' '))
    return names


class _ClassInfo:
    def __init__(self, name: str, path_rel: str, node: ast.ClassDef):
        self.name = name
        self.path_rel = path_rel
        self.node = node
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.lock_attrs: Set[str] = set()
        # attr -> candidate class names (constructor / annotation / hint)
        self.attr_types: Dict[str, Set[str]] = {}
        # attr -> candidate VALUE class names for Dict[...]-typed attrs
        self.attr_value_types: Dict[str, Set[str]] = {}


class _FuncInfo:
    def __init__(self, qual: str, path_rel: str, node: ast.FunctionDef,
                 cls: Optional[_ClassInfo]):
        self.qual = qual            # "Class.method" or "module:fn"
        self.path_rel = path_rel
        self.node = node
        self.cls = cls


class _Model:
    """The scanned universe: classes, functions, module locks."""

    def __init__(self) -> None:
        self.classes: Dict[str, _ClassInfo] = {}
        self.functions: Dict[str, _FuncInfo] = {}
        # module path -> {function name -> qual}
        self.module_funcs: Dict[str, Dict[str, str]] = {}
        # module path -> {global name known to be a lock}
        self.module_locks: Dict[str, Set[str]] = {}


def _is_lock_ctor(call: ast.AST) -> bool:
    return (isinstance(call, ast.Call)
            and dotted_name(call.func) in _LOCK_CTORS)


def _collect(model: _Model, tree: ast.Module, path_rel: str) -> None:
    model.module_funcs.setdefault(path_rel, {})
    model.module_locks.setdefault(path_rel, set())
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and _is_lock_ctor(stmt.value):
            model.module_locks[path_rel].add(stmt.targets[0].id)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = f"{path_rel}:{stmt.name}"
            model.functions[qual] = _FuncInfo(qual, path_rel, stmt, None)
            model.module_funcs[path_rel][stmt.name] = qual
        if isinstance(stmt, ast.ClassDef):
            info = _ClassInfo(stmt.name, path_rel, stmt)
            # Last definition wins on name collisions across modules —
            # acceptable for this repo (class names are unique).
            model.classes[stmt.name] = info
            for item in stmt.body:
                if isinstance(item, ast.FunctionDef):
                    info.methods[item.name] = item
                    qual = f"{stmt.name}.{item.name}"
                    model.functions[qual] = _FuncInfo(qual, path_rel, item,
                                                      info)


def _self_attr_target(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _infer_types(model: _Model) -> None:
    """Fill attr_types / attr_value_types per class from constructor
    calls, parameter annotations, AnnAssign annotations and name hints."""
    for info in model.classes.values():
        param_anns: Dict[str, List[str]] = {}
        init = info.methods.get("__init__")
        if init is not None:
            for arg in list(init.args.args) + list(init.args.kwonlyargs):
                param_anns[arg.arg] = _ann_class_names(arg.annotation)
        for method in info.methods.values():
            for stmt in ast.walk(method):
                target = None
                value = None
                ann = None
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                    target, value = stmt.targets[0], stmt.value
                elif isinstance(stmt, ast.AnnAssign):
                    target, value, ann = stmt.target, stmt.value, \
                        stmt.annotation
                attr = _self_attr_target(target) if target is not None \
                    else None
                if attr is None:
                    continue
                if _is_lock_ctor(value):
                    info.lock_attrs.add(attr)
                    continue
                cands: Set[str] = set()
                # Constructor calls anywhere in the value (covers
                # ``x if cond else Metrics()``).
                if value is not None:
                    for sub in ast.walk(value):
                        if isinstance(sub, ast.Call):
                            leaf = dotted_name(sub.func).rsplit(".", 1)[-1]
                            if leaf in model.classes:
                                cands.add(leaf)
                    # Plain parameter pass-through: use its annotation.
                    if isinstance(value, ast.Name):
                        cands.update(n for n in param_anns.get(value.id, [])
                                     if n in model.classes)
                ann_names = _ann_class_names(ann)
                cands.update(n for n in ann_names if n in model.classes)
                if cands:
                    info.attr_types.setdefault(attr, set()).update(cands)
                # Dict[...]-valued attrs: remember candidate VALUE types
                # so ``self.jobs.get(k)`` locals resolve.
                if ann_names and ann_names[0] in ("Dict", "dict",
                                                  "OrderedDict"):
                    vals = {n for n in ann_names[1:] if n in model.classes}
                    if vals:
                        info.attr_value_types.setdefault(attr,
                                                         set()).update(vals)
                hint = _ATTR_NAME_HINTS.get(attr)
                if hint and hint in model.classes:
                    info.attr_types.setdefault(attr, set()).add(hint)


def _local_types(fn: _FuncInfo, model: _Model) -> Dict[str, Set[str]]:
    """Best-effort local-variable class inference inside one function
    (seeded from the function's own annotated parameters)."""
    out: Dict[str, Set[str]] = {}
    cls = fn.cls
    for arg in (list(fn.node.args.args) + list(fn.node.args.kwonlyargs)):
        cands = {n for n in _ann_class_names(arg.annotation)
                 if n in model.classes}
        if cands:
            out.setdefault(arg.arg, set()).update(cands)
    for stmt in ast.walk(fn.node):
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            continue
        name = stmt.targets[0].id
        value = stmt.value
        cands: Set[str] = set()
        if isinstance(value, ast.Call):
            leaf = dotted_name(value.func).rsplit(".", 1)[-1]
            if leaf in model.classes:
                cands.add(leaf)
            # self.<dictattr>.get(...) → the dict's value type.
            if (cls is not None and isinstance(value.func, ast.Attribute)
                    and value.func.attr in ("get", "pop", "setdefault")):
                recv = _self_attr_target(value.func.value)
                if recv is not None and recv in cls.attr_value_types:
                    cands.update(cls.attr_value_types[recv])
        elif cls is not None:
            attr = _self_attr_target(value)
            if attr is not None and attr in cls.attr_types:
                cands.update(cls.attr_types[attr])
        if cands:
            out.setdefault(name, set()).update(cands)
    return out


def _lock_id(expr: ast.AST, fn: _FuncInfo, model: _Model,
             locals_: Dict[str, Set[str]]) -> Optional[str]:
    """Node id in the order graph for a lock-shaped with-item."""
    if _lockish(expr) is None:
        return None
    # self.X
    attr = _self_attr_target(expr)
    if attr is not None and fn.cls is not None:
        return f"{fn.cls.name}.{attr}"
    # self.a.b (lock owned by a typed attribute, e.g. self._cs.lock)
    if isinstance(expr, ast.Attribute):
        owner_attr = _self_attr_target(expr.value)
        if owner_attr is not None and fn.cls is not None:
            for owner_cls in sorted(fn.cls.attr_types.get(owner_attr, ())):
                if expr.attr in model.classes[owner_cls].lock_attrs:
                    return f"{owner_cls}.{expr.attr}"
        # local.b
        if isinstance(expr.value, ast.Name):
            for owner_cls in sorted(locals_.get(expr.value.id, ())):
                if expr.attr in model.classes[owner_cls].lock_attrs:
                    return f"{owner_cls}.{expr.attr}"
    # module-level lock
    if isinstance(expr, ast.Name):
        if expr.id in model.module_locks.get(fn.path_rel, ()):
            return f"{fn.path_rel}:{expr.id}"
        # function-local lock: node scoped to the function
        return f"{fn.qual}:{expr.id}"
    # Unresolvable lock-shaped expression: a conservative local node.
    return f"{fn.qual}:{dotted_name(expr)}"


def _resolve_call(call: ast.Call, fn: _FuncInfo, model: _Model,
                  locals_: Dict[str, Set[str]]) -> List[str]:
    """Call site → candidate function quals in the scanned universe."""
    func = call.func
    targets: List[str] = []
    if isinstance(func, ast.Attribute):
        method = func.attr
        # self.m()
        if isinstance(func.value, ast.Name) and func.value.id == "self" \
                and fn.cls is not None:
            if method in fn.cls.methods:
                return [f"{fn.cls.name}.{method}"]
            return []
        # self.attr.m() / local.m()
        owner_classes: Set[str] = set()
        attr = _self_attr_target(func.value)
        if attr is not None and fn.cls is not None:
            owner_classes = fn.cls.attr_types.get(attr, set())
        elif isinstance(func.value, ast.Name):
            owner_classes = locals_.get(func.value.id, set())
        for owner in sorted(owner_classes):
            if method in model.classes[owner].methods:
                targets.append(f"{owner}.{method}")
        return targets
    if isinstance(func, ast.Name):
        qual = model.module_funcs.get(fn.path_rel, {}).get(func.id)
        if qual is not None:
            return [qual]
    return []


def _direct_blocking(call: ast.Call) -> Optional[str]:
    callee = dotted_name(call.func)
    leaf = callee.rsplit(".", 1)[-1]
    if (leaf in _BLOCKING_ATTRS
            or callee == "time.sleep"
            or any(callee.startswith(p) for p in _BLOCKING_PREFIXES)
            or ".clientset." in f".{callee}."):
        return callee
    return None


class _Summaries:
    """Per-function transitive summaries with cycle-safe memoization."""

    def __init__(self, model: _Model):
        self.model = model
        self._locals: Dict[str, Dict[str, Set[str]]] = {}
        self._acq: Dict[str, Set[str]] = {}
        self._blk: Dict[str, Dict[str, str]] = {}  # qual -> {reason: site}
        self._stack: Set[str] = set()

    def locals_of(self, qual: str) -> Dict[str, Set[str]]:
        if qual not in self._locals:
            self._locals[qual] = _local_types(self.model.functions[qual],
                                              self.model)
        return self._locals[qual]

    def acquires(self, qual: str) -> Set[str]:
        """Lock ids ``qual`` may acquire, transitively."""
        if qual in self._acq:
            return self._acq[qual]
        if qual in self._stack:
            return set()  # recursion: the fixpoint converges from below
        self._stack.add(qual)
        fn = self.model.functions[qual]
        locals_ = self.locals_of(qual)
        out: Set[str] = set()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = _lock_id(item.context_expr, fn, self.model,
                                   locals_)
                    if lid:
                        out.add(lid)
            elif isinstance(node, ast.Call):
                for target in _resolve_call(node, fn, self.model, locals_):
                    out |= self.acquires(target)
        self._stack.discard(qual)
        self._acq[qual] = out
        return out

    def blocks(self, qual: str) -> Dict[str, str]:
        """Blocking reasons reachable from ``qual``: reason -> witness
        ("file:line"). Direct blocking calls made on a lock-shaped
        receiver (``cond.wait``) are excluded — they release."""
        if qual in self._blk:
            return self._blk[qual]
        if qual in self._stack:
            return {}
        self._stack.add(qual)
        fn = self.model.functions[qual]
        locals_ = self.locals_of(qual)
        out: Dict[str, str] = {}
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            reason = _direct_blocking(node)
            if reason is not None:
                recv = node.func.value if isinstance(node.func,
                                                     ast.Attribute) else None
                if recv is not None and _lockish(recv):
                    continue  # wait/notify on a lock releases it
                out.setdefault(reason,
                               f"{fn.path_rel}:{node.lineno}")
                continue
            for target in _resolve_call(node, fn, self.model, locals_):
                for reason, site in self.blocks(target).items():
                    out.setdefault(reason, site)
        self._stack.discard(qual)
        self._blk[qual] = out
        return out


def _enclosing_with_lock_ids(node: ast.AST, fn: _FuncInfo, model: _Model,
                             locals_: Dict[str, Set[str]]) -> List[str]:
    out: List[str] = []
    for anc in ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                lid = _lock_id(item.context_expr, fn, model, locals_)
                if lid:
                    out.append(lid)
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break  # nested defs (handlers) have their own frames
    return out


def _canonical_cycle(cycle: List[str]) -> str:
    """Rotation-invariant rendering: start at the lexicographic min."""
    i = cycle.index(min(cycle))
    rotated = cycle[i:] + cycle[:i]
    return "->".join(rotated + [rotated[0]])


def run(root: Path) -> List[Finding]:
    model = _Model()
    trees: List[Tuple[ast.Module, str]] = []
    seen: Set[Path] = set()
    for parts in SCAN:
        for path in iter_py_files(root, *parts):
            if path in seen:
                continue
            seen.add(path)
            tree = parse_file(path)
            if tree is None:
                continue
            attach_parents(tree)
            path_rel = rel(root, path)
            trees.append((tree, path_rel))
            _collect(model, tree, path_rel)
    _infer_types(model)
    sums = _Summaries(model)

    findings: List[Finding] = []
    # edge -> (witness file, line, description)
    edge_witness: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    reported_hops: Set[str] = set()

    for qual, fn in model.functions.items():
        locals_ = sums.locals_of(qual)
        for node in ast.walk(fn.node):
            held: List[str] = []
            acquired_here: List[str] = []
            if isinstance(node, ast.With):
                held = _enclosing_with_lock_ids(node, fn, model, locals_)
                for item in node.items:
                    lid = _lock_id(item.context_expr, fn, model, locals_)
                    if lid:
                        acquired_here.append(lid)
            elif isinstance(node, ast.Call):
                held = _enclosing_with_lock_ids(node, fn, model, locals_)
                if held:
                    for target in _resolve_call(node, fn, model, locals_):
                        acquired_here.extend(sums.acquires(target))
                        blocked = sums.blocks(target)
                        if blocked:
                            reason, site = sorted(blocked.items())[0]
                            callee = dotted_name(node.func)
                            key = f"blocking-hop:{fn.path_rel}:" \
                                  f"{qual.rsplit(':', 1)[-1]}:{callee}"
                            if key not in reported_hops:
                                reported_hops.add(key)
                                findings.append(Finding(
                                    RULE, fn.path_rel, node.lineno,
                                    f"call {callee}() under `with "
                                    f"{held[0]}:` reaches blocking "
                                    f"{reason}() (at {site}) — every "
                                    f"thread contending on the lock "
                                    f"serializes behind that I/O",
                                    key=key))
            if not held or not acquired_here:
                continue
            # A `*_locked` method's own lock is held by its caller, so
            # an edge onto it from the enclosing with is reentrant
            # context, not nesting — same-node edges are dropped below.
            for h in held:
                for a in acquired_here:
                    if h == a:
                        continue
                    edge_witness.setdefault(
                        (h, a), (fn.path_rel, node.lineno, qual))

    # Cycle detection over the final edge set (DFS, each cycle once).
    adj: Dict[str, List[str]] = {}
    for a, b in edge_witness:
        adj.setdefault(a, []).append(b)
    for nbrs in adj.values():
        nbrs.sort()
    reported_cycles: Set[str] = set()

    def dfs(start: str) -> None:
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in adj.get(node, ()):
                if nxt == start:
                    cyc = _canonical_cycle(path)
                    if cyc in reported_cycles:
                        continue
                    reported_cycles.add(cyc)
                    wfile, wline, wqual = edge_witness[(node, start)]
                    sites = "; ".join(
                        f"{a}->{b} at "
                        f"{edge_witness[(a, b)][0]}:"
                        f"{edge_witness[(a, b)][1]}"
                        for a, b in zip(path, path[1:] + [start]))
                    findings.append(Finding(
                        RULE, wfile, wline,
                        f"lock-order cycle {cyc} — threads entering it "
                        f"from different arcs deadlock (witnesses: "
                        f"{sites})",
                        key=f"cycle:{cyc}"))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))

    for start in sorted(adj):
        dfs(start)
    return findings
