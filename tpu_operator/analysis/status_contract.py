"""Rule ``status-contract``: heartbeat body ↔ sanitizer ↔ status schema,
and metric-name hygiene.

The heartbeat chain has three hand-maintained layers: the payload POSTs a
body (``payload/heartbeat.py``), the status server sanitizes it down to the
CRD shape (``controller/statusserver.py record_heartbeat``), and the strict
status schema admits it (``schema.py status_schema``). A key present
upstream but missing downstream is *silently dropped* telemetry (the
lost-one-shot class of bug); the rule enforces

    posted-keys − envelope  ⊆  sanitized-keys  ⊆  schema lastHeartbeat keys

(``namespace``/``name`` are the routing envelope the server consumes, never
status payload). Metric hygiene, same spirit:

- every registered/emitted metric name appears in ``docs/`` and in at least
  one file under ``tests/`` (an undocumented metric is invisible to
  operators; an untested one silently breaks);
- every ``inc``/``observe``/``set_gauge`` call site with a literal name
  refers to a registered or emitted metric (counters auto-register, so a
  typo'd call site otherwise creates a parallel, forever-zero family).

Keys: ``posted-unsanitized:<key>``, ``sanitized-unschema:<key>``,
``metric-undocumented:<name>``, ``metric-untested:<name>``,
``metric-unregistered:<name>``.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Set

from tpu_operator.analysis.base import Finding, attach_parents, ancestors, \
    dotted_name, iter_py_files, parse_file, rel, str_const

RULE = "status-contract"

HEARTBEAT = "tpu_operator/payload/heartbeat.py"
STATUSSERVER = "tpu_operator/controller/statusserver.py"
SCHEMA = "tpu_operator/apis/tpujob/v1alpha1/schema.py"

# Routing envelope: consumed by the server to find the job, never persisted.
ENVELOPE = {"namespace", "name"}

_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def _resolve_loop_name(node: ast.Name) -> Optional[Set[str]]:
    """A subscript index that is a Name bound by an enclosing literal
    ``for``-loop: resolve the set of string values it takes. Handles both
    ``for k in ("a", "b")`` and ``for a, b in (("x", "y"), ...)``."""
    for anc in ancestors(node):
        if not isinstance(anc, ast.For):
            continue
        target, it = anc.target, anc.iter
        if not isinstance(it, (ast.Tuple, ast.List)):
            continue
        if isinstance(target, ast.Name) and target.id == node.id:
            values = {str_const(e) for e in it.elts}
            return {v for v in values if v is not None} or None
        if isinstance(target, ast.Tuple):
            for pos, el in enumerate(target.elts):
                if isinstance(el, ast.Name) and el.id == node.id:
                    values = set()
                    for e in it.elts:
                        if isinstance(e, (ast.Tuple, ast.List)) \
                                and len(e.elts) > pos:
                            v = str_const(e.elts[pos])
                            if v is not None:
                                values.add(v)
                    return values or None
    return None


def _dict_keys_of(tree: ast.Module, var: str) -> Dict[str, int]:
    """String keys flowing into dict variable ``var``: literal keys of
    ``var = {...}`` / ``var: T = {...}`` assignments and ``var[...] = ...``
    stores (loop-bound index names resolved against literal tuples)."""
    attach_parents(tree)
    out: Dict[str, int] = {}

    def record(value: Optional[str], line: int) -> None:
        if value is not None:
            out.setdefault(value, line)

    for node in ast.walk(tree):
        value_node = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value_node = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target = node.target
            value_node = node.value
        else:
            continue
        if isinstance(target, ast.Name) and target.id == var \
                and isinstance(value_node, ast.Dict):
            for k in value_node.keys:
                if k is not None:
                    record(str_const(k), k.lineno)
        if isinstance(target, ast.Subscript) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == var:
            idx = target.slice
            const = str_const(idx)
            if const is not None:
                record(const, idx.lineno)
            elif isinstance(idx, ast.Name):
                for v in _resolve_loop_name(idx) or ():
                    record(v, idx.lineno)
    return out


def _schema_heartbeat_keys(tree: ast.Module) -> Set[str]:
    """Property keys of the ``lastHeartbeat`` object in status_schema()."""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "status_schema":
            for d in ast.walk(node):
                if not isinstance(d, ast.Dict):
                    continue
                for k, v in zip(d.keys, d.values):
                    if k is not None and str_const(k) == "lastHeartbeat" \
                            and isinstance(v, ast.Call) and v.args \
                            and isinstance(v.args[0], ast.Dict):
                        return {str_const(kk) for kk in v.args[0].keys
                                if kk is not None and str_const(kk)}
    return set()


# --- metric hygiene ----------------------------------------------------------

def _registered_metrics(tree: ast.Module) -> Dict[str, int]:
    """First args of ``.register(name, mtype, ...)`` calls; a Name first
    arg bound by a literal for-loop (``for name in (...): register(name``)
    resolves to every value it takes."""
    attach_parents(tree)
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "register" and len(node.args) >= 2:
            mtype = str_const(node.args[1])
            if mtype not in ("counter", "gauge", "histogram"):
                continue
            name = str_const(node.args[0])
            if name:
                out.setdefault(name, node.lineno)
            elif isinstance(node.args[0], ast.Name):
                for value in _resolve_loop_name(node.args[0]) or ():
                    out.setdefault(value, node.lineno)
    return out


def _emitted_metrics(tree: ast.Module) -> Dict[str, int]:
    """Gauge names emitted ad hoc by ``render_metrics``: ``emit(name, ...)``
    first args, ``METRIC_PREFIX + "name"`` concatenations, and loop-table
    metric names (lowercase, underscore-bearing string literals)."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name == "render_metrics"):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                    and sub.func.id == "emit" and sub.args:
                name = str_const(sub.args[0])
                if name:
                    out.setdefault(name, sub.lineno)
            if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Add) \
                    and isinstance(sub.left, ast.Name) \
                    and sub.left.id == "METRIC_PREFIX":
                name = str_const(sub.right)
                if name:
                    out.setdefault(name, sub.lineno)
            value = str_const(sub)
            if value and "_" in value and _METRIC_NAME_RE.match(value):
                out.setdefault(value, sub.lineno)
    return out


def _metric_call_sites(root: Path) -> Dict[str, List[str]]:
    """Literal metric names at ``*.inc/observe/set_gauge`` call sites on
    metrics-ish receivers, across the control plane."""
    sites: Dict[str, List[str]] = {}
    for path in iter_py_files(root, "tpu_operator"):
        if "analysis" in path.parts:
            continue
        tree = parse_file(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("inc", "observe", "set_gauge") \
                    and node.args \
                    and "metrics" in dotted_name(node.func.value).lower():
                name = str_const(node.args[0])
                if name:
                    sites.setdefault(name, []).append(
                        f"{rel(root, path)}:{node.lineno}")
    return sites


def _grep_tree(base: Path, suffixes: tuple) -> str:
    chunks: List[str] = []
    if base.is_dir():
        for path in sorted(base.rglob("*")):
            if path.suffix in suffixes and path.is_file():
                try:
                    chunks.append(path.read_text(encoding="utf-8"))
                except OSError:
                    continue
    return "\n".join(chunks)


def run(root: Path) -> List[Finding]:
    findings: List[Finding] = []
    hb_path, ss_path = root / HEARTBEAT, root / STATUSSERVER
    hb_tree, ss_tree = parse_file(hb_path), parse_file(ss_path)
    schema_tree = parse_file(root / SCHEMA)

    if hb_tree is not None and ss_tree is not None:
        posted = _dict_keys_of(hb_tree, "body")
        ss_keys = _dict_keys_of(ss_tree, "hb")
        sanitized = set(ss_keys)
        for key, line in sorted(posted.items()):
            if key in ENVELOPE or key in sanitized:
                continue
            findings.append(Finding(
                RULE, rel(root, hb_path), line,
                f"heartbeat body key {key!r} is posted but "
                f"statusserver.record_heartbeat silently drops it "
                f"(not sanitized into the status copy)",
                key=f"posted-unsanitized:{key}"))
        if schema_tree is not None:
            schema_keys = _schema_heartbeat_keys(schema_tree)
            if schema_keys:
                for key, line in sorted(ss_keys.items()):
                    if key not in schema_keys:
                        findings.append(Finding(
                            RULE, rel(root, ss_path), line,
                            f"sanitized heartbeat key {key!r} is not in the "
                            f"status schema's lastHeartbeat object — strict "
                            f"admission would wedge every later status "
                            f"write",
                            key=f"sanitized-unschema:{key}"))

    # -- metric hygiene -------------------------------------------------------
    if ss_tree is not None:
        registered = _registered_metrics(ss_tree)
        emitted = _emitted_metrics(ss_tree)
        known = {**emitted, **registered}
        docs_text = _grep_tree(root / "docs", (".md",))
        tests_text = _grep_tree(root / "tests", (".py",))
        for name, line in sorted(known.items()):
            if docs_text and name not in docs_text:
                findings.append(Finding(
                    RULE, rel(root, ss_path), line,
                    f"metric {name!r} is exposed but never documented "
                    f"under docs/", key=f"metric-undocumented:{name}"))
            if tests_text and name not in tests_text:
                findings.append(Finding(
                    RULE, rel(root, ss_path), line,
                    f"metric {name!r} is exposed but no test under tests/ "
                    f"references it", key=f"metric-untested:{name}"))
        for name, where in sorted(_metric_call_sites(root).items()):
            if name not in known:
                path_str, _, line_str = where[0].rpartition(":")
                findings.append(Finding(
                    RULE, path_str, int(line_str),
                    f"metric call site uses unregistered name {name!r} "
                    f"(counters auto-create, so a typo here splits the "
                    f"series silently)",
                    key=f"metric-unregistered:{name}"))
    return findings
