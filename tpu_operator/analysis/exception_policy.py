"""Rule ``exceptions``: error-handling policy on controller paths.

The reconcile engine's failure contract is "log-and-requeue or re-raise":
an exception swallowed silently on a controller path is a stuck job with no
trail (the review-caught drift bugs of PRs 1-5 were all of this shape).
Checked over ``tpu_operator/{controller,trainer,client,cmd}``:

- **bare-except** — ``except:`` catches SystemExit/KeyboardInterrupt too;
  always flagged.
- **silent-except** — a handler whose body is a lone ``pass`` (any
  exception type): the swallow leaves no log line. Justified teardown
  paths go on the allowlist.
- **broad-except** — ``except Exception/BaseException`` whose body neither
  re-raises nor calls a logger: the error is converted to silence.
- **exit-code** — retryable exit codes (137/143) written as literals
  instead of the named constants (``bootstrap.EXIT_RETRYABLE``,
  ``policy.PREEMPTION_EXIT_CODES``); checked across all of
  ``tpu_operator/`` since the payload side owns the contract's other end.

Keys: ``bare-except:<file>:<func>``, ``silent-except:<file>:<func>``,
``broad-except:<file>:<func>``, ``exit-code:<file>:<func>``.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional

from tpu_operator.analysis.base import Finding, ancestors, attach_parents, \
    dotted_name, iter_py_files, parse_file, rel

RULE = "exceptions"

SCOPE = (
    ("tpu_operator", "controller"),
    ("tpu_operator", "trainer"),
    ("tpu_operator", "client"),
    ("tpu_operator", "cmd"),
)

RETRYABLE_EXIT_CODES = {137, 143}

_BROAD = {"Exception", "BaseException"}
_LOGGER_METHODS = {"debug", "info", "warning", "error", "exception",
                   "critical", "log"}


def _func_name(node: ast.AST) -> str:
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc.name
    return "<module>"


def _is_broad(type_node: Optional[ast.AST]) -> bool:
    if type_node is None:
        return False  # bare handled separately
    names = []
    if isinstance(type_node, ast.Tuple):
        names = [dotted_name(e) for e in type_node.elts]
    else:
        names = [dotted_name(type_node)]
    return any(n.rsplit(".", 1)[-1] in _BROAD for n in names)


def _handles(handler: ast.ExceptHandler, what: str) -> bool:
    for node in ast.walk(handler):
        if what == "raise" and isinstance(node, ast.Raise):
            return True
        if what == "log" and isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _LOGGER_METHODS:
            receiver = dotted_name(node.func.value).lower()
            if "log" in receiver:
                return True
    return False


def _check_handlers(tree: ast.Module, path_rel: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        fn = _func_name(node)
        pass_only = (len(node.body) == 1
                     and isinstance(node.body[0], ast.Pass))
        if node.type is None:
            findings.append(Finding(
                RULE, path_rel, node.lineno,
                f"bare `except:` in {fn}() catches SystemExit/"
                f"KeyboardInterrupt — name the exception",
                key=f"bare-except:{path_rel}:{fn}"))
        elif pass_only:
            findings.append(Finding(
                RULE, path_rel, node.lineno,
                f"exception swallowed silently (pass-only handler) in "
                f"{fn}() — log it, re-raise, or allowlist with a "
                f"justification", key=f"silent-except:{path_rel}:{fn}"))
        elif _is_broad(node.type) and not _handles(node, "raise") \
                and not _handles(node, "log"):
            findings.append(Finding(
                RULE, path_rel, node.lineno,
                f"broad `except {ast.unparse(node.type)}` in {fn}() "
                f"neither logs nor re-raises — failures on this path "
                f"vanish", key=f"broad-except:{path_rel}:{fn}"))
    return findings


def _check_exit_codes(tree: ast.Module, path_rel: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        callee = dotted_name(node.func)
        if callee not in ("SystemExit", "sys.exit", "os._exit", "exit"):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, int) \
                and arg.value in RETRYABLE_EXIT_CODES:
            fn = _func_name(node)
            findings.append(Finding(
                RULE, path_rel, node.lineno,
                f"retryable exit code {arg.value} written as a literal in "
                f"{fn}() — use the named constant (EXIT_RETRYABLE / "
                f"PREEMPTION_EXIT_CODES) so the operator contract stays "
                f"greppable", key=f"exit-code:{path_rel}:{fn}"))
    return findings


def run(root: Path) -> List[Finding]:
    """One parse per file: exit-code literals are checked across all of
    tpu_operator/, handler policy only on the controller-path SCOPE."""
    findings: List[Finding] = []
    scope_prefixes = tuple("/".join(parts) + "/" for parts in SCOPE)
    for path in iter_py_files(root, "tpu_operator"):
        if "analysis" in path.parts:
            continue
        tree = parse_file(path)
        if tree is None:
            continue
        attach_parents(tree)
        path_rel = rel(root, path)
        if path_rel.startswith(scope_prefixes):
            findings += _check_handlers(tree, path_rel)
        findings += _check_exit_codes(tree, path_rel)
    return findings
