"""Rule ``escape``: shared mutable state reachable from ≥2 thread
entrypoints with no guarding lock and no ``guarded-by`` annotation.

The ``concurrency`` rule enforces annotations that exist; this rule
hunts the state nobody annotated. Two scopes:

1. **Class attributes.** For every class that provably runs on more
   than one thread — it spawns a ``Thread(target=self.X)``, registers
   ``self.X``/lambda handlers via ``add_event_handler`` (informer
   dispatch threads), or defines HTTP handler methods (``do_GET``/
   ``do_POST``) — partition its methods into *thread domains*: the
   closure of each thread root under same-class calls, plus "main"
   (everything else). An attribute MUTATED outside ``__init__`` in one
   domain and TOUCHED in another, where the mutation is not under any
   lock-shaped ``with`` and the attribute carries no ``# guarded-by:``
   annotation, has escaped the lock discipline — exactly the shape of
   an informer handler list appended during a live dispatch.

   Mutation = assignment/augassign/del of ``self.X``, subscript stores,
   or calls to known mutator methods (``append``/``add``/``pop``/...).
   Attributes that ARE synchronization objects (``threading.Event``,
   ``queue.Queue`` — internally locked) are exempt.

2. **Module globals.** In modules that spawn threads or register
   callbacks onto foreign threads (``Thread(...)``,
   ``register_event_listener``), a module-level variable mutated from
   any function without a lock and without an annotation is flagged.
   Separately, a module-level ``# guarded-by: <lock>`` annotation is
   ENFORCED on every mutation site regardless of the module's thread
   profile — an annotation is a contract, not a comment.

Keys: ``attr:<file>:<Class>.<attr>`` and ``global:<file>:<name>``.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set

from tpu_operator.analysis.base import Finding, ancestors, attach_parents, \
    comment_annotations, dotted_name, iter_py_files, parse_file, rel
from tpu_operator.analysis.concurrency import SCAN, _lockish

RULE = "escape"

_MUTATORS = {"append", "add", "pop", "remove", "clear", "update", "extend",
             "discard", "popitem", "insert", "setdefault", "appendleft",
             "move_to_end", "set"}

# Constructors whose instances synchronize internally (or ARE the
# synchronization): mutations through them are not escapes.
_SYNC_CTORS = {"threading.Event", "Event", "threading.Lock", "Lock",
               "threading.RLock", "RLock", "threading.Condition",
               "Condition", "threading.Semaphore", "Semaphore",
               "queue.Queue", "Queue", "threading.local",
               "lockdep.lock", "lockdep.rlock", "lockdep.condition"}

_HTTP_ROOTS = {"do_GET", "do_POST", "do_PUT", "do_DELETE"}

_CALLBACK_REGISTRARS = {"register_event_listener", "add_event_handler",
                        "install"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _method_of(node: ast.AST, cls: ast.ClassDef) -> Optional[str]:
    """Name of the class-body method whose frame contains ``node``
    (None for nested defs — they are their own threads' business)."""
    chain = [node] + list(ancestors(node))
    for i, anc in enumerate(chain):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            parent = chain[i + 1] if i + 1 < len(chain) else None
            return anc.name if parent is cls else None
    return None


def _under_lock(node: ast.AST) -> bool:
    for anc in ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                if _lockish(item.context_expr):
                    return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
    return False


def _thread_roots(cls: ast.ClassDef) -> Set[str]:
    """Method names that are thread entrypoints of this class."""
    roots: Set[str] = set()
    for method in cls.body:
        if isinstance(method, ast.FunctionDef) and method.name in _HTTP_ROOTS:
            roots.add(method.name)
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func)
        leaf = callee.rsplit(".", 1)[-1]
        if callee in ("threading.Thread", "Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    roots.update(_ref_methods(kw.value))
        elif leaf in _CALLBACK_REGISTRARS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                roots.update(_ref_methods(arg))
    return roots


def _ref_methods(expr: ast.AST) -> Set[str]:
    """Method names referenced by ``self.X`` or by lambdas calling
    ``self.X(...)`` inside ``expr``."""
    out: Set[str] = set()
    for node in ast.walk(expr):
        attr = _self_attr(node)
        if attr is not None:
            out.add(attr)
    return out


def _domain_closure(cls: ast.ClassDef, methods: Dict[str, ast.FunctionDef],
                    root: str) -> Set[str]:
    """Methods reachable from ``root`` through same-class calls."""
    seen: Set[str] = set()
    stack = [root]
    while stack:
        name = stack.pop()
        if name in seen or name not in methods:
            continue
        seen.add(name)
        for node in ast.walk(methods[name]):
            if isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                if callee is not None and callee in methods:
                    stack.append(callee)
    return seen


def _self_syncing_classes(trees: List[ast.Module]) -> Set[str]:
    """Classes in the scanned universe that own a lock (their methods
    synchronize internally — RateLimitingQueue, Metrics, ...): calling
    into an instance is not an escape, so attributes holding one are
    exempt like Queue/Event."""
    out: Set[str] = set()
    for tree in trees:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and dotted_name(sub.func) in _SYNC_CTORS:
                    out.add(node.name)
                    break
    return out


def _check_class(cls: ast.ClassDef, path_rel: str, notes: Dict[int, str],
                 selfsync: Set[str]) -> List[Finding]:
    methods = {m.name: m for m in cls.body
               if isinstance(m, ast.FunctionDef)}
    roots = _thread_roots(cls)
    if not roots:
        return []
    domains: Dict[str, Set[str]] = {
        root: _domain_closure(cls, methods, root) for root in sorted(roots)
    }
    threaded = set().union(*domains.values()) if domains else set()
    domains["<main>"] = {m for m in methods if m not in threaded}

    # guarded-by-annotated attrs (any line of the class body) and
    # sync-object attrs are exempt.
    annotated: Set[str] = set()
    sync_attrs: Set[str] = set()
    for node in ast.walk(cls):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        attr = _self_attr(target) if target is not None else None
        if attr is None:
            continue
        end = getattr(node, "end_lineno", None) or node.lineno
        if any(line in notes for line in range(node.lineno, end + 1)):
            annotated.add(attr)
        value = getattr(node, "value", None)
        if value is not None:
            for sub in ast.walk(value):
                if not isinstance(sub, ast.Call):
                    continue
                ctor = dotted_name(sub.func)
                if ctor in _SYNC_CTORS \
                        or ctor.rsplit(".", 1)[-1] in selfsync:
                    sync_attrs.add(attr)
                    break
        ann = getattr(node, "annotation", None)
        if ann is not None:
            for sub in ast.walk(ann):
                if isinstance(sub, ast.Name) and sub.id in selfsync:
                    sync_attrs.add(attr)

    # Per attr: domains that mutate it (outside __init__, outside locks,
    # outside *_locked methods) and domains that touch it at all.
    mutated_in: Dict[str, Dict[str, int]] = {}  # attr -> {domain: line}
    touched_in: Dict[str, Set[str]] = {}
    for node in ast.walk(cls):
        attr, is_mutation = _classify_access(node)
        if attr is None or attr in annotated or attr in sync_attrs:
            continue
        method = _method_of(node, cls)
        if method is None or method == "__init__":
            continue
        for domain, members in domains.items():
            if method not in members:
                continue
            touched_in.setdefault(attr, set()).add(domain)
            if is_mutation and not method.endswith("_locked") \
                    and not _under_lock(node):
                mutated_in.setdefault(attr, {}).setdefault(domain,
                                                           node.lineno)

    findings: List[Finding] = []
    for attr in sorted(mutated_in):
        mut_domains = mutated_in[attr]
        others = touched_in.get(attr, set()) - set(mut_domains)
        # Escaped: mutated in ≥2 domains, or mutated in one and touched
        # in another.
        if len(mut_domains) < 2 and not others:
            continue
        domain, line = sorted(mut_domains.items())[0]
        all_domains = sorted(set(mut_domains) | others)
        findings.append(Finding(
            RULE, path_rel, line,
            f"{cls.name}.{attr} is mutated without a lock but reachable "
            f"from {len(all_domains)} thread domains "
            f"({', '.join(all_domains)}) — guard it and annotate "
            f"`# guarded-by: <lock>`, or justify via allowlist",
            key=f"attr:{path_rel}:{cls.name}.{attr}"))
    return findings


def _classify_access(node: ast.AST) -> tuple:
    """(attr, is_mutation) for one AST node touching ``self.X``."""
    # self.X = / self.X op= / del self.X
    if isinstance(node, ast.Attribute):
        attr = _self_attr(node)
        if attr is None:
            return None, False
        ctx = node.ctx
        if isinstance(ctx, (ast.Store, ast.Del)):
            return attr, True
        # self.X[...] = value
        parent = getattr(node, "parent", None)
        if isinstance(parent, ast.Subscript) \
                and isinstance(parent.ctx, (ast.Store, ast.Del)):
            return attr, True
        # self.X.append(...) etc.
        if isinstance(parent, ast.Attribute) and parent.attr in _MUTATORS:
            grand = getattr(parent, "parent", None)
            if isinstance(grand, ast.Call) and grand.func is parent:
                return attr, True
        return attr, False
    return None, False


# --- module-level globals -----------------------------------------------------

def _check_module(tree: ast.Module, path_rel: str,
                  notes: Dict[int, str]) -> List[Finding]:
    # Module-level variables and their guarded-by annotations.
    module_vars: Set[str] = set()
    annotated: Dict[str, str] = {}
    sync_vars: Set[str] = set()
    for stmt in tree.body:
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, ast.AnnAssign):
            targets = [stmt.target]
        for target in targets:
            if not isinstance(target, ast.Name):
                continue
            module_vars.add(target.id)
            end = getattr(stmt, "end_lineno", None) or stmt.lineno
            for line in range(stmt.lineno, end + 1):
                if line in notes:
                    annotated[target.id] = notes[line]
                    break
            value = getattr(stmt, "value", None)
            if isinstance(value, ast.Call) \
                    and dotted_name(value.func) in _SYNC_CTORS:
                sync_vars.add(target.id)

    threaded_module = any(
        isinstance(node, ast.Call)
        and (dotted_name(node.func) in ("threading.Thread", "Thread")
             or dotted_name(node.func).rsplit(".", 1)[-1]
             in _CALLBACK_REGISTRARS)
        for node in ast.walk(tree))

    findings: List[Finding] = []
    flagged: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        declared_global: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Global):
                declared_global.update(sub.names)
        # Names BOUND locally (params, non-global assignments): a mutator
        # call on one of these is local state shadowing a module name,
        # not a global mutation.
        local_bound: Set[str] = {
            a.arg for a in (list(node.args.args)
                            + list(node.args.kwonlyargs)
                            + list(node.args.posonlyargs))}
        for extra in (node.args.vararg, node.args.kwarg):
            if extra is not None:
                local_bound.add(extra.arg)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) \
                    and isinstance(sub.ctx, ast.Store) \
                    and sub.id not in declared_global:
                local_bound.add(sub.id)
        for sub in ast.walk(node):
            name, line = _global_mutation(sub, declared_global,
                                          local_bound,
                                          module_vars, sync_vars)
            if name is None or name in flagged:
                continue
            guard = annotated.get(name)
            if guard is not None:
                if not _under_named_lock(sub, guard):
                    flagged.add(name)
                    findings.append(Finding(
                        RULE, path_rel, line,
                        f"module global {name} is annotated guarded-by "
                        f"{guard} but {node.name}() mutates it outside "
                        f"`with {guard}:`",
                        key=f"global:{path_rel}:{name}"))
            elif threaded_module and not _under_lock(sub):
                flagged.add(name)
                findings.append(Finding(
                    RULE, path_rel, line,
                    f"module global {name} is mutated by {node.name}() "
                    f"without a lock in a module that runs callbacks/"
                    f"threads — guard it and annotate "
                    f"`# guarded-by: <lock>`, or justify via allowlist",
                    key=f"global:{path_rel}:{name}"))
    return findings


def _global_mutation(node: ast.AST, declared_global: Set[str],
                     local_bound: Set[str], module_vars: Set[str],
                     sync_vars: Set[str]) -> tuple:
    """(name, line) when ``node`` mutates a module global, else (None, 0)."""
    # NAME = / NAME op= (requires a `global` declaration to bind)
    if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                 (ast.Store, ast.Del)):
        if node.id in declared_global and node.id in module_vars \
                and node.id not in sync_vars:
            return node.id, node.lineno
        return None, 0
    # MODULE_VAR.append(...) / MODULE_VAR[...] = ... — in-place mutation
    # needs no `global` declaration (and a declared-global receiver is
    # still the module object); only a LOCALLY-bound name shadowing the
    # module var is exempt.
    if isinstance(node, ast.Attribute) and node.attr in _MUTATORS \
            and isinstance(node.value, ast.Name):
        name = node.value.id
        parent = getattr(node, "parent", None)
        if (name in module_vars and name not in sync_vars
                and name not in local_bound
                and isinstance(parent, ast.Call) and parent.func is node):
            return name, node.lineno
    if isinstance(node, ast.Subscript) \
            and isinstance(node.ctx, (ast.Store, ast.Del)) \
            and isinstance(node.value, ast.Name) \
            and node.value.id in module_vars \
            and node.value.id not in local_bound \
            and node.value.id not in sync_vars:
        return node.value.id, node.lineno
    return None, 0


def _under_named_lock(node: ast.AST, lock: str) -> bool:
    lock = lock.removeprefix("self.")
    for anc in ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                name = dotted_name(item.context_expr).removeprefix("self.")
                if name == lock:
                    return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
    return False


def run(root: Path) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Path] = set()
    parsed: List[tuple] = []
    for parts in SCAN:
        for path in iter_py_files(root, *parts):
            if path in seen:
                continue
            seen.add(path)
            tree = parse_file(path)
            if tree is None:
                continue
            attach_parents(tree)
            parsed.append((tree, rel(root, path),
                           comment_annotations(path, "guarded-by")))
    selfsync = _self_syncing_classes([t for t, _p, _n in parsed])
    for tree, path_rel, notes in parsed:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings += _check_class(node, path_rel, notes, selfsync)
        findings += _check_module(tree, path_rel, notes)
    return findings
