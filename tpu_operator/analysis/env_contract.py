"""Rule ``env-contract``: operator env injection ↔ payload env reads.

``trainer/replicas.py`` injects the pod env contract (``TPUJOB_*``,
``JAX_*``, ``TPU_*``, ``MEGASCALE_*``); ``tpu_operator/payload/`` consumes
it. Drift here is silent at review time and fatal (or dead weight) at job
runtime, so both directions are checked:

- **injected-unread**: an env var the operator injects that no payload
  module references. Either dead plumbing (delete it) or an external
  contract (libtpu/XLA reads it, not our code) — the latter goes on the
  allowlist with a justification.
- **read-uninjected**: an env var the payload reads that the operator never
  injects. Either a missing injection or a user-provided knob (template
  env, developer override) — again allowlist with justification.

Injections are collected from dict literals assigned to a name ``env`` and
``env["X"] = ...`` stores in replicas.py. Reads are any full-literal
occurrence of an env-shaped name in payload code outside docstrings (this
deliberately counts ``ENV_VAR = "TPU_CHECKPOINT_DIR"`` constants and
``e.get(ENV_VAR)`` indirection as reads).

Keys: ``injected-unread:<NAME>``, ``read-uninjected:<NAME>``.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List

from tpu_operator.analysis.base import ENV_NAME_RE, Finding, \
    iter_py_files, non_docstring_strings, parse_file, rel, str_const

RULE = "env-contract"

INJECTOR = "tpu_operator/trainer/replicas.py"
PAYLOAD_DIR = ("tpu_operator", "payload")

# Env dict variable names on the injection side.
_ENV_NAMES = {"env"}


def _injected(tree: ast.Module) -> Dict[str, int]:
    """Env names injected by replicas.py: keys of dict literals assigned to
    ``env`` plus ``env["X"] = ...`` subscript stores."""
    out: Dict[str, int] = {}

    def record(node: ast.AST) -> None:
        value = str_const(node)
        if value is not None and ENV_NAME_RE.match(value):
            out.setdefault(value, node.lineno)

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
            if len(targets) == 1 and isinstance(targets[0], ast.Name) \
                    and targets[0].id in _ENV_NAMES \
                    and isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    if k is not None:
                        record(k)
            if len(targets) == 1 and isinstance(targets[0], ast.Subscript) \
                    and isinstance(targets[0].value, ast.Name) \
                    and targets[0].value.id in _ENV_NAMES:
                record(targets[0].slice)
    return out


def _payload_reads(root: Path) -> Dict[str, str]:
    """Env names referenced by payload code (name → ``file:line`` of first
    reference), docstrings excluded."""
    reads: Dict[str, str] = {}
    for path in iter_py_files(root, *PAYLOAD_DIR):
        tree = parse_file(path)
        if tree is None:
            continue
        for value, line in non_docstring_strings(tree):
            if ENV_NAME_RE.match(value):
                reads.setdefault(value, f"{rel(root, path)}:{line}")
    return reads


def run(root: Path) -> List[Finding]:
    findings: List[Finding] = []
    injector_path = root / INJECTOR
    tree = parse_file(injector_path)
    if tree is None:
        return findings
    injected = _injected(tree)
    reads = _payload_reads(root)

    for name, line in sorted(injected.items()):
        if name not in reads:
            findings.append(Finding(
                RULE, rel(root, injector_path), line,
                f"env var {name} is injected into the pod but never read "
                f"by tpu_operator/payload/ — dead plumbing, or an external "
                f"contract that belongs on the allowlist",
                key=f"injected-unread:{name}"))

    for name, where in sorted(reads.items()):
        if name in injected:
            continue
        path_str, _, line_str = where.rpartition(":")
        findings.append(Finding(
            RULE, path_str, int(line_str),
            f"payload reads env var {name} which trainer/replicas.py never "
            f"injects — missing injection, or a user/developer knob that "
            f"belongs on the allowlist",
            key=f"read-uninjected:{name}"))
    return findings
