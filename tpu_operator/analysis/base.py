"""Shared plumbing for the analysis rules: findings, allowlists, AST helpers.

Every rule reports :class:`Finding` objects carrying a stable ``rule`` id
and a stable ``key`` (what the finding is *about*, independent of line
numbers), so allowlist entries survive unrelated edits. The allowlist file
format is one suppression per line::

    <rule-id>  <key>        # justification (required by convention)

Rules operate on a *root directory* (parsed with ``ast``, never imported),
which is what lets the self-tests run each rule against fixture trees with
seeded violations.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple


@dataclass(frozen=True)
class Finding:
    """One analyzer hit: ``path:line: [rule] message`` with a stable key."""

    rule: str
    path: str       # repo-relative, forward slashes
    line: int
    message: str
    key: str        # stable allowlist handle (no line numbers)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message} " \
               f"(key: {self.key})"


@dataclass
class Allowlist:
    """Per-rule suppression set parsed from hack/analyze_allowlist.txt."""

    entries: Set[Tuple[str, str]] = field(default_factory=set)

    @classmethod
    def load(cls, path: Path) -> "Allowlist":
        entries: Set[Tuple[str, str]] = set()
        if path.is_file():
            for raw in path.read_text(encoding="utf-8").splitlines():
                line = raw.split("#", 1)[0].strip()
                if not line:
                    continue
                parts = line.split(None, 1)
                if len(parts) == 2:
                    entries.add((parts[0], parts[1].strip()))
        return cls(entries)

    def allows(self, finding: Finding) -> bool:
        return (finding.rule, finding.key) in self.entries

    def unused(self, findings: Iterable[Finding]) -> Set[Tuple[str, str]]:
        hit = {(f.rule, f.key) for f in findings}
        return {e for e in self.entries if e not in hit}


# --- source / AST helpers ----------------------------------------------------

ENV_NAME_RE = re.compile(r"^(TPUJOB|JAX|TPU|MEGASCALE|DMLC)[A-Z0-9]*_[A-Z0-9_]+$")


def rel(root: Path, path: Path) -> str:
    return path.relative_to(root).as_posix()


# Parsed-tree cache shared by every rule in one analyzer run: the
# concurrency / lock-order / escape trio walks the same SCAN universe,
# and re-parsing ~25 files per rule tripled the gate's AST cost. Keyed
# on (path, mtime_ns, size) so a fixture tree rewritten in place (the
# self-tests do this) never serves a stale tree; bounded so long test
# sessions can't grow it without limit. Trees are shared read-only
# (attach_parents is idempotent).
_PARSE_CACHE: Dict[Tuple[str, int, int], Optional[ast.Module]] = {}
_PARSE_CACHE_MAX = 512


def parse_file(path: Path) -> Optional[ast.Module]:
    try:
        st = path.stat()
        key = (str(path), st.st_mtime_ns, st.st_size)
    except OSError:
        return None
    if key in _PARSE_CACHE:
        return _PARSE_CACHE[key]
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"),
                         filename=str(path))
    except (OSError, SyntaxError):
        tree = None
    if len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
        _PARSE_CACHE.clear()
    _PARSE_CACHE[key] = tree
    return tree


def iter_py_files(root: Path, *parts: str) -> List[Path]:
    """All .py files under ``root/parts...`` (a file path is returned
    as-is), sorted for deterministic findings."""
    base = root.joinpath(*parts)
    if base.is_file():
        return [base]
    if not base.is_dir():
        return []
    return sorted(p for p in base.rglob("*.py") if "__pycache__" not in p.parts)


def attach_parents(tree: ast.AST) -> None:
    """Annotate every node with ``.parent`` (rules walk ancestor chains)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]


def ancestors(node: ast.AST) -> Iterable[ast.AST]:
    cur = getattr(node, "parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "parent", None)


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` → ``"X"``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted rendering of an expression (``self.clientset.pods``
    → ``"self.clientset.pods"``); unknown parts render as ``?``."""
    if isinstance(node, ast.Attribute):
        return f"{dotted_name(node.value)}.{node.attr}"
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return dotted_name(node.func) + "()"
    return "?"


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def module_str_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments (used to resolve
    ``e.get(ENV_VAR)``-style indirection)."""
    consts: Dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            value = str_const(stmt.value)
            if value is not None:
                consts[stmt.targets[0].id] = value
    return consts


def comment_annotations(path: Path, tag: str) -> Dict[int, str]:
    """Map line number → value for ``# <tag>: <value>`` trailing comments
    (ast drops comments, so annotations come from the token stream)."""
    # Matches anywhere in a comment token so the tag can share a line with
    # prose ("# heap of (...); guarded-by: _cond").
    pattern = re.compile(rf"{re.escape(tag)}:\s*(\S+)")
    out: Dict[int, str] = {}
    try:
        tokens = tokenize.generate_tokens(
            io.StringIO(path.read_text(encoding="utf-8")).readline)
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                m = pattern.search(tok.string)
                if m:
                    out[tok.start[0]] = m.group(1)
    except (OSError, tokenize.TokenError, SyntaxError):
        pass
    return out


def non_docstring_strings(tree: ast.Module) -> List[Tuple[str, int]]:
    """Every string constant with its line, excluding doc-position strings
    (an env var named in a docstring is documentation, not a read)."""
    doc_nodes: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) \
                    and str_const(body[0].value) is not None:
                doc_nodes.add(id(body[0].value))
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if id(node) in doc_nodes:
            continue
        value = str_const(node)
        if value is not None:
            out.append((value, node.lineno))
    return out


def camel_to_snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()
