"""Sharded reconcile workqueue: N rate-limited queues with stable
key-hash routing.

The single :class:`~tpu_operator.client.workqueue.RateLimitingQueue`
serializes every worker behind one condition variable; at fleet scale
(5k jobs churning admission/status writes) that lock is the next convoy.
Sharding by key hash gives each worker its own queue AND gives every job
*worker affinity*: one key always lands on one shard, so — on top of each
shard's own dirty/processing-set dedup — no two workers can ever
reconcile the same job concurrently, by construction rather than by
coordination.

Routing uses ``zlib.crc32`` (stable across processes and runs, unlike
Python's per-process-randomized ``hash``), so tests can pin keys to
shards and a restart shards the same way.

The wrapper mirrors the RateLimitingQueue surface the controller, the
deadline manager, and the status server already consume (``add``,
``add_after``, ``add_rate_limited``, ``forget``, ``done``, ``shutdown``,
``__len__``, the telemetry gauges); only ``get`` changes shape — a worker
pops its own shard.
"""

from __future__ import annotations

import time
import zlib
from typing import Any, Callable, List, Optional

from tpu_operator.client.workqueue import (
    DEFAULT_BASE_DELAY,
    DEFAULT_MAX_DELAY,
    RateLimitingQueue,
)


class ShardedWorkQueue:
    """N per-shard RateLimitingQueues behind one routing facade."""

    def __init__(self, shards: int,
                 base_delay: float = DEFAULT_BASE_DELAY,
                 max_delay: float = DEFAULT_MAX_DELAY,
                 clock: Callable[[], float] = time.monotonic,
                 metrics: Optional[Any] = None):
        self.shards: List[RateLimitingQueue] = [
            RateLimitingQueue(base_delay=base_delay, max_delay=max_delay,
                              clock=clock, metrics=metrics)
            for _ in range(max(1, int(shards)))
        ]

    @property
    def num_shards(self) -> int:
        return len(self.shards)

    def shard_for(self, item: Any) -> int:
        return zlib.crc32(str(item).encode()) % len(self.shards)

    def _q(self, item: Any) -> RateLimitingQueue:
        return self.shards[self.shard_for(item)]

    # -- routing surface (the RateLimitingQueue API, keyed by item) ------------

    def add(self, item: Any) -> None:
        self._q(item).add(item)

    def add_rate_limited(self, item: Any) -> None:
        self._q(item).add_rate_limited(item)

    def add_after(self, item: Any, delay: float, timer: bool = False) -> None:
        self._q(item).add_after(item, delay, timer=timer)

    def forget(self, item: Any) -> None:
        self._q(item).forget(item)

    def done(self, item: Any) -> None:
        self._q(item).done(item)

    def num_requeues(self, item: Any) -> int:
        return self._q(item).num_requeues(item)

    def get(self, timeout: Optional[float] = None,
            shard: Optional[int] = None) -> Optional[Any]:
        """Pop the given shard's queue — each worker owns exactly one.
        ``shard=None`` (synchronous harnesses driving the controller via
        ``process_next_work_item`` with no shard) sweeps every shard
        instead of silently draining only shard 0 — keys hashed elsewhere
        must never be stranded."""
        if shard is not None:
            return self.shards[shard].get(timeout=timeout)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            for q in self.shards:
                item = q.get(timeout=0)
                if item is not None:
                    return item
            if self.is_shutdown:
                return None
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(0.005)

    # -- lifecycle / telemetry (aggregated over shards) ------------------------

    def shutdown(self) -> None:
        for q in self.shards:
            q.shutdown()

    @property
    def is_shutdown(self) -> bool:
        return all(q.is_shutdown for q in self.shards)

    def __len__(self) -> int:
        return sum(len(q) for q in self.shards)

    def unfinished_work_seconds(self) -> float:
        return sum(q.unfinished_work_seconds() for q in self.shards)

    def longest_running_processor_seconds(self) -> float:
        return max(q.longest_running_processor_seconds()
                   for q in self.shards)
