"""Status-writeback rate limiting: a global token bucket over
*non-critical* status PUTs.

At fleet scale the status writes that matter (phase/attempt transitions —
the restart machinery's source of truth) are a small fraction of the
writes a naive controller issues: heartbeat telemetry, replica-state
roll-up deltas, and queue-position updates would turn 5k jobs into 5k
PUT/s against the apiserver. The limiter gates only the non-critical
class: a deferred write leaves the in-memory status dirty, the
TrainingJob arms a retry obligation, and the coalesced state lands in ONE
PUT when a token frees — the same ride-along idiom the heartbeat
coalescing already uses.

Critical writes (phase, attempt, state, reason, backoff transitions and
setup's spec persistence) NEVER wait here: correctness transitions must
not queue behind telemetry.
"""

from __future__ import annotations

import time
from typing import Callable
from tpu_operator.util import lockdep


class WritebackLimiter:
    """Token bucket: ``qps`` sustained PUT/s with a ``burst`` reservoir.

    ``allow()`` consumes a token when available; callers defer the write
    otherwise and use ``retry_after()`` to arm the retry obligation.
    Thread-safe: every reconcile worker shares one instance."""

    def __init__(self, qps: float, burst: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        if qps <= 0:
            raise ValueError("qps must be > 0 (use no limiter for unlimited)")
        self._qps = float(qps)
        self._burst = float(burst if burst > 0 else max(1.0, qps))
        self._clock = clock
        self._lock = lockdep.lock("WritebackLimiter._lock")
        self._tokens = self._burst  # guarded-by: _lock
        self._last = clock()  # guarded-by: _lock

    def _refill_locked(self) -> None:
        now = self._clock()
        self._tokens = min(self._burst,
                           self._tokens + (now - self._last) * self._qps)
        self._last = now

    def allow(self) -> bool:
        with self._lock:
            self._refill_locked()
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def retry_after(self) -> float:
        """Seconds until a token will be available (0 when one already is)."""
        with self._lock:
            self._refill_locked()
            if self._tokens >= 1.0:
                return 0.0
            return (1.0 - self._tokens) / self._qps
