"""Fleet scheduler: slice-inventory admission, priority preemption, and
the fleet-scale control-plane plumbing (sharded workqueues, writeback
rate limiting) that lets ONE operator drive thousands of TPUJobs.

The reference tf-operator reconciled every job independently with no
admission control — a pod-creating free-for-all that cannot model a
cluster's finite TPU slice inventory (SURVEY.md). This package is the
many-jobs half of the control plane:

- ``inventory``  — the capacity model: (accelerator resource, topology) →
  whole slices, fed from static config or discovered node objects, plus
  the per-job gang demand derivation.
- ``fleet``      — the admission queue: gangs admit only when their WHOLE
  demand fits, fair-share across queues, priority preemption of the
  lowest-priority newest-admitted job, rebuilt from informer caches on
  operator restart (no persisted scheduler state).
- ``sharding``   — N rate-limited workqueues with stable key-hash routing,
  so reconcile workers scale without ever processing one job concurrently.
- ``writeback``  — a global token bucket over non-critical status PUTs, so
  5k jobs' telemetry churn does not become 5k PUT/s.
"""
