"""Slice-inventory model: how much TPU hardware the cluster has, in the
unit gangs are scheduled in — whole slices of one (accelerator resource,
topology) shape.

A TPU slice is indivisible: a v4-32 (topology 2x2x4) is acquired and
released as a unit, and a JAX gang needs *all* of its slices live before
any member computes (SURVEY.md §7 gang hard part). The inventory therefore
counts slices, not chips: capacity is ``"<resource>:<topology>" → N whole
slices`` and a job's demand is ``spec.numSlices`` slices of its shape.

Two feeds:

- **static config** (``ControllerConfig.slice_inventory`` /
  ``--slice-inventory``) — the admin declares what the cluster owns;
- **discovered node objects** (:func:`SliceInventory.from_node_objects`) —
  nodes advertising a TPU resource in ``status.allocatable`` are grouped by
  (resource, topology label, slice-id label) and each distinct slice id
  counts one slice. Nodes without a slice-id label count one slice each
  (single-host slices).

Empty inventory = no admission control (every demand fits — the pre-fleet
behavior, and what keeps every existing test/job flow unchanged). A key
absent from a *non-empty* inventory is "unmodeled" and also always fits:
queueing a job forever on a config typo is strictly worse than
over-admitting it.

Not thread-safe on its own: the FleetScheduler owns one instance and
guards it with its lock.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

from tpu_operator.apis.tpujob.v1alpha1.types import (
    DEFAULT_SCHEDULING_QUEUE,
    TPU_RESOURCE_PREFIX,
    TPUJobSpec,
)

# Node labels the discovery path reads (GKE publishes the topology label on
# TPU node pools; the slice-id label groups the hosts of one multi-host
# slice — absent on single-host slices).
NODE_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
NODE_SLICE_ID_LABEL = "tpuoperator.dev/slice-id"


def slice_key(resource: str, topology: str) -> str:
    """Canonical inventory key: ``<resource>:<topology>`` ('' topology ok)."""
    return f"{resource}:{topology}"


def node_ready(node: Dict[str, Any]) -> bool:
    """Whether a node object is schedulable per its Ready condition.
    Only an EXPLICIT Ready=False/Unknown excludes the node — absent
    conditions mean ready, so hand-built node manifests (every test
    before the fake-kubelet layer existed) keep counting."""
    for cond in ((node.get("status") or {}).get("conditions") or []):
        if (cond or {}).get("type") == "Ready":
            return str(cond.get("status", "True")) == "True"
    return True


def tpu_resource_name(template: Optional[Dict[str, Any]]) -> str:
    """First ``cloud-tpus.google.com/*`` resource name a pod template
    requests ('' when it requests none) — the accelerator half of the
    job's slice shape (the chip *count* rides on the template too, but
    slices are the scheduling unit, so only the shape matters here)."""
    pod_spec = (template or {}).get("spec") or {}
    for container in pod_spec.get("containers") or []:
        resources = container.get("resources") or {}
        for section in ("requests", "limits"):
            for res_name in resources.get(section) or {}:
                if str(res_name).startswith(TPU_RESOURCE_PREFIX):
                    return str(res_name)
    return ""


def scheduling_params(spec: TPUJobSpec) -> Tuple[int, str]:
    """(priority, queue) the admission queue uses for a spec — the ONE
    place the absent-block/empty-queue fallback lives, so the live
    reconcile path and the controller's restart rebuild can never drift
    into different fair-share buckets."""
    sched = spec.scheduling
    if sched is None:
        return 0, DEFAULT_SCHEDULING_QUEUE
    return sched.priority, sched.queue or DEFAULT_SCHEDULING_QUEUE


def job_demand(spec: TPUJobSpec) -> Optional[Tuple[str, int]]:
    """(inventory key, whole slices) one gang of this job occupies, or
    None for a zero-footprint job (no replica set requests TPU chips) —
    those admit unconditionally and are never tracked.

    This is the RIGID demand (``spec.numSlices``). Elastic jobs
    (``spec.elastic``) layer a range on top: callers derive
    ``[minSlices, maxSlices]`` via ``trainer/elastic.elastic_range`` and
    pass the preferred size as the demand with ``min_slices`` alongside
    (scheduler/fleet.py grants the largest fitting size in the range and
    accounts the GRANT, not this number)."""
    for rs in spec.replica_specs:
        resource = tpu_resource_name(rs.template)
        if resource:
            return (slice_key(resource, spec.tpu_topology),
                    max(1, spec.num_slices))
    return None


class SliceInventory:
    """Slice-granular capacity ledger: reserve on admission, release on
    teardown/TTL/terminal failure. Reservations may exceed capacity via
    :meth:`reserve` — the rebuild-from-cache path re-admits jobs that
    already hold hardware, and refusing them would be fiction; the
    over-commit drains as those jobs finish."""

    def __init__(self, capacity: Optional[Dict[str, int]] = None):
        self._capacity: Dict[str, int] = {
            str(k): int(v) for k, v in (capacity or {}).items()}
        self._used: Dict[str, int] = {}

    @classmethod
    def from_config(cls, config: Any) -> "SliceInventory":
        """Static feed: ``ControllerConfig.slice_inventory``."""
        return cls(getattr(config, "slice_inventory", None) or {})

    @classmethod
    def from_node_objects(cls, nodes: Iterable[Dict[str, Any]]
                          ) -> "SliceInventory":
        """Discovery feed: count distinct slices per (resource, topology)
        across node objects (see module docstring for the label contract)."""
        slices: Dict[str, set] = {}
        for node in nodes:
            md = node.get("metadata") or {}
            labels = md.get("labels") or {}
            if not node_ready(node):
                # A NotReady node's slices are not schedulable capacity:
                # counting them would admit gangs onto dead hardware. A
                # node with no conditions at all stays ready (back-compat
                # with static manifests that never carry conditions).
                continue
            allocatable = ((node.get("status") or {})
                           .get("allocatable") or {})
            resource = next(
                (str(r) for r in allocatable
                 if str(r).startswith(TPU_RESOURCE_PREFIX)), "")
            if not resource:
                continue
            key = slice_key(resource, str(labels.get(NODE_TOPOLOGY_LABEL,
                                                     "")))
            # One slice per distinct slice id; an unlabeled node is its own
            # single-host slice (keyed by node name).
            sid = labels.get(NODE_SLICE_ID_LABEL) or f"node:{md.get('name', '')}"
            slices.setdefault(key, set()).add(sid)
        return cls({k: len(v) for k, v in slices.items()})

    # -- queries ---------------------------------------------------------------

    @property
    def empty(self) -> bool:
        return not self._capacity

    def capacities(self) -> Dict[str, int]:
        """The modeled capacity map (copy) — what a live inventory refresh
        feeds into :meth:`set_capacity` on another instance."""
        return dict(self._capacity)

    def set_capacity(self, capacity: Dict[str, int]) -> None:
        """Swap the capacity model in place, PRESERVING reservations: the
        node-informer feed (nodes added/removed/relabeled) changes what
        the cluster owns, not what admitted gangs hold. A shrink below
        current usage leaves the shape transiently over-committed — the
        truth on the ground (the gangs are physically running) — and
        drains as they finish, exactly like the restart-rebuild path."""
        self._capacity = {str(k): int(v) for k, v in (capacity or {}).items()}

    def modeled(self, shape: str) -> bool:
        return shape in self._capacity

    def capacity(self, shape: str) -> Optional[int]:
        """Total modeled slices of a shape (None when unmodeled) — what
        distinguishes 'waiting for capacity' from 'can NEVER fit'."""
        return self._capacity.get(shape)

    def free(self, shape: str) -> int:
        if shape not in self._capacity:
            return 0
        return self._capacity[shape] - self._used.get(shape, 0)

    def fits(self, shape: str, slices: int) -> bool:
        """Whether a whole gang of ``slices`` slices fits right now.
        Unmodeled shapes always fit (module docstring)."""
        if shape not in self._capacity:
            return True
        return self.free(shape) >= slices

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """Introspection view: shape → {capacity, used}."""
        return {k: {"capacity": c, "used": self._used.get(k, 0)}
                for k, c in sorted(self._capacity.items())}

    # -- accounting ------------------------------------------------------------

    def reserve(self, shape: str, slices: int) -> None:
        """Unchecked reservation (callers decide via fits(); the rebuild
        path reserves past capacity on purpose). Unmodeled shapes are not
        tracked — there is nothing to account against."""
        if shape in self._capacity:
            self._used[shape] = self._used.get(shape, 0) + slices

    def release(self, shape: str, slices: int) -> None:
        if shape in self._capacity:
            self._used[shape] = max(0, self._used.get(shape, 0) - slices)
