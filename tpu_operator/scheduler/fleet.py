"""The fleet admission queue: gang-level admission, fair share across
queues, and priority preemption over the slice inventory.

One instance lives on the controller and every TrainingJob consults it
from its reconcile:

- ``ensure_admitted`` — the reconcile-time gate before any gang create: a
  job whose whole demand fits is admitted (capacity reserved); otherwise
  it parks in the pending queue and the TrainingJob shows phase
  ``Queued``. Admission order is strict priority first, then fair share
  (the queue holding the smallest slice share goes first), then FIFO.
  An unfittable head blocks later arrivals OF ITS OWN SLICE SHAPE on
  purpose — a large gang is not starved by a stream of small later
  arrivals (K8s gang schedulers: Kueue, Volcano, same call) — but never
  blocks other shapes, whose pools are independent capacity.
- ``pop_eviction`` — preemption delivery: when a higher-priority pending
  job cannot fit, the rebalance marks the cheapest sufficient victim set
  (lowest priority first, newest admitted first, same slice shape) and
  enqueues their reconciles; each victim's reconcile pops its directive,
  tears the gang down as a *preemption-kind* restart (the PR-2 budget —
  eviction must not burn crash-loop budget) and re-queues.
- ``release`` — teardown/TTL/terminal failure/suspend return the slices
  and trigger a rebalance; newly fitting jobs are admitted and their keys
  enqueued so their reconciles promote them out of ``Queued``.

Restart-vs-release contract: ordinary whole-group restarts (crash,
preemption-by-kubelet, stall) RETAIN their reservation through
teardown/Backoff — the gang is coming back, and releasing would let a
queued job steal the slot out from under every restart. Only scheduler
eviction, suspension, and terminal/teardown paths release.

Restart rebuild: no scheduler state is persisted. A job that already
holds hardware (phase Running, or Creating with live pods in the informer
cache) is *force-admitted* on its first post-restart reconcile — capacity
may transiently over-commit past config, which is the truth on the ground
and drains as jobs finish.

Exported metrics (registered in controller/statusserver.py):
``tpujob_queue_depth{queue}``, ``tpujob_admission_latency_seconds``,
``tpujob_preemptions_total``.
"""

from __future__ import annotations

import collections
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpu_operator.apis.tpujob.v1alpha1.types import DEFAULT_SCHEDULING_QUEUE
from tpu_operator.obs import timeline as timeline_mod
from tpu_operator.scheduler.inventory import SliceInventory
from tpu_operator.util import joblife, lockdep

log = logging.getLogger(__name__)

# Bound on queue names tracked for gauge zeroing: spec.scheduling.queue is
# user-supplied, and a tenant minting a queue name per run would otherwise
# grow the tracking set AND the tpujob_queue_depth series forever (the
# PR-1 event-dedup-cache slow-leak class). Idle queues beyond the cap are
# dropped from tracking and their series removed from the registry.
QUEUE_GAUGE_CAP = 256

# Per-queue admission-wait sample window for the fleet rollup's
# p50/p95: the newest N admissions per queue, not a lifetime histogram —
# the rollup answers "what does THIS queue cost right now".
QUEUE_WAIT_SAMPLES = 256


@dataclass
class _Entry:
    """One job known to the scheduler (pending or admitted)."""

    key: str          # ns/name — the reconcile key
    uid: str          # object UID: a re-created job is a new entry
    demand_key: str   # inventory key (resource:topology)
    slices: int       # pending: the PREFERRED (max) size; admitted: GRANTED
    priority: int
    queue: str
    seq: int          # arrival order (FIFO tie-break)
    enqueued_at: float = 0.0   # pending: when it first queued (latency)
    admit_seq: int = 0         # admitted: admission order (victim pick)
    forced: bool = field(default=False)  # rebuild path (no latency sample)
    # Demand exceeds the shape's TOTAL modeled capacity: can never fit,
    # must never head-block the shape, and the job's status says so.
    impossible: bool = field(default=False)
    # Smallest admissible world size (elastic jobs: spec.elastic
    # minSlices; rigid jobs: == slices). Admission fits/victim-selection
    # tests run against THIS — an elastic gang shrinks instead of
    # queueing — while the grant prefers ``slices``.
    min_slices: int = field(default=0)
    # The size the job ASKED for (its spec's preferred maximum),
    # refreshed from the demand on every admission-gate pass. An
    # admitted entry granted below this is running shrunk — a tight
    # admission grant or a straggler-shed cap — which victim selection
    # reads: evicting an already-degraded gang costs less goodput than
    # evicting a healthy full-width one.
    preferred: int = field(default=0)
    # Serving fleet (spec.mode: serve) and its minimum slice footprint
    # (minReplicas for slice-per-replica fleets; the whole footprint for
    # fixed-size ones). Victim selection reads :meth:`serve_at_min`:
    # a fleet with nothing left to shrink goes dark if evicted, where a
    # training gang resumes from its checkpoint.
    serve: bool = field(default=False)
    serve_min_slices: int = field(default=0)

    def floor(self) -> int:
        """The size this job must at least be granted to run."""
        return self.min_slices or self.slices

    def shrunk(self) -> bool:
        """Running below the preferred size."""
        return bool(self.preferred) and self.slices < self.preferred

    def serve_at_min(self) -> bool:
        """A serving fleet already at its replica floor — evicting it
        takes live traffic capacity to zero slack, so it ranks as the
        WORST victim in its priority band."""
        return self.serve and self.slices <= (self.serve_min_slices
                                              or self.slices)


class FleetScheduler:
    """Admission queue + preemption over a :class:`SliceInventory`.

    ``enqueue`` is the controller's workqueue add — the scheduler uses it
    to wake the reconciles of jobs it just admitted or marked for
    eviction. ``clock`` is the wall clock (admission latency)."""

    def __init__(self, inventory: Optional[SliceInventory] = None,
                 enqueue: Optional[Callable[[str], None]] = None,
                 metrics: Optional[Any] = None,
                 clock: Callable[[], float] = time.time):
        self._enqueue = enqueue
        self._metrics = metrics
        self._clock = clock
        self._lock = lockdep.lock("FleetScheduler._lock")
        self._inventory = inventory or SliceInventory()  # guarded-by: _lock
        self._admitted: Dict[str, _Entry] = joblife.track(
            "FleetScheduler._admitted")  # per-job: release; guarded-by: _lock
        self._pending: Dict[str, _Entry] = joblife.track(
            "FleetScheduler._pending")  # per-job: release; guarded-by: _lock
        # key -> (victim uid, reason): UID-scoped so a directive aimed at
        # a deleted job can never preempt a same-name successor.
        self._evicting: Dict[str, Tuple[str, str]] = joblife.track(
            "FleetScheduler._evicting")  # per-job: release; guarded-by: _lock
        self._known_queues: set = set()  # gauge zeroing; guarded-by: _lock
        # queue name -> recent admission waits (seconds, newest last).
        # Keyed by QUEUE (not job), bounded by the same eviction pattern
        # as the depth gauges — queue-name churn cannot grow it.
        self._queue_waits: Dict[str, "collections.deque"] = {}  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock

    # -- the reconcile-time gate -----------------------------------------------

    def ensure_admitted(self, key: str, *, uid: str,
                        demand: Optional[Tuple[str, int]],
                        priority: int = 0,
                        queue: str = DEFAULT_SCHEDULING_QUEUE,
                        holds_hardware: Any = False,
                        min_slices: Optional[int] = None,
                        held_slices: Optional[int] = None,
                        serve: bool = False,
                        serve_min_slices: int = 0) -> bool:
        """True when ``key`` may (continue to) run its gang.

        ``demand`` is ``inventory.job_demand(spec)``; None = zero-footprint
        job, admitted unconditionally and never tracked. ``holds_hardware``
        is the rebuild signal (bool or zero-arg callable, evaluated only
        past the admitted fast path): the job's persisted phase/children
        show it already owns its slices, so refuse-and-queue would be
        fiction — reserve unconditionally instead (see module docstring).

        Elastic jobs (``spec.elastic``) pass ``min_slices`` < the demand
        slices: the demand is the PREFERRED (max) size, and admission
        grants the largest size in ``[min_slices, slices]`` that fits —
        shrinking instead of queueing. The GRANTED size is what the
        inventory accounts (never the spec's full size — a shrunk gang
        must not reserve phantom capacity it isn't using), readable via
        :meth:`granted_slices` and re-negotiated per attempt via
        :meth:`resize`. ``held_slices`` is the rebuild companion: a
        restarted operator re-reserves what the job's persisted
        ``status.elastic`` says it actually holds, not the spec maximum.

        A mid-attempt spec edit keeps the original reservation until the
        next attempt boundary — :meth:`resize` (the gang re-create path)
        is where sizes change, never under a live gang's feet."""
        if demand is None:
            return True
        demand_key, slices = demand
        min_req = min(min_slices, slices) if min_slices else slices
        wake: List[str] = []
        with self._lock:
            ent = self._admitted.get(key)
            if ent is not None and ent.uid == uid:
                # Keep the preferred size tracking the live spec: a
                # shrunk-vs-full reading taken against a stale demand
                # would mis-rank victims after a spec resize. The serve
                # floor likewise follows the live spec (a minReplicas
                # edit changes which fleets rank as at-min victims).
                ent.preferred = slices
                ent.serve = serve
                ent.serve_min_slices = int(serve_min_slices)
                return True
            if ent is not None:
                # Same name, new UID: the old job's reservation is stale.
                self._release_locked(ent)
            if callable(holds_hardware):
                holds_hardware = holds_hardware()
            if holds_hardware:
                held = held_slices if held_slices else slices
                self._seq += 1
                self._inventory.reserve(demand_key, held)
                self._admitted[key] = _Entry(
                    key=key, uid=uid, demand_key=demand_key, slices=held,
                    priority=priority, queue=queue, seq=self._seq,
                    admit_seq=self._seq, forced=True, min_slices=min_req,
                    preferred=slices, serve=serve,
                    serve_min_slices=int(serve_min_slices))
                self._pending.pop(key, None)
                self._update_gauges_locked()
                return True
            pend = self._pending.get(key)
            if (pend is None or pend.uid != uid
                    or pend.demand_key != demand_key
                    or pend.slices != slices or pend.min_slices != min_req
                    or pend.priority != priority or pend.queue != queue):
                self._seq += 1
                self._pending[key] = _Entry(
                    key=key, uid=uid, demand_key=demand_key, slices=slices,
                    priority=priority, queue=queue, seq=self._seq,
                    min_slices=min_req, preferred=slices, serve=serve,
                    serve_min_slices=int(serve_min_slices),
                    enqueued_at=(pend.enqueued_at
                                 if pend is not None and pend.uid == uid
                                 else self._clock()))
            wake = self._rebalance_locked()
            admitted = key in self._admitted
        self._notify(wake, skip=key)
        return admitted

    def granted_slices(self, key: str) -> Optional[int]:
        """The world size ``key``'s admitted reservation holds (None when
        not admitted) — what an elastic job's attempt actually gangs at."""
        with self._lock:
            ent = self._admitted.get(key)
            return None if ent is None else ent.slices

    def resize(self, key: str, *, uid: str, min_slices: int,
               max_slices: int) -> Optional[int]:
        """Re-negotiate an admitted elastic job's reservation at a gang
        (re)create boundary: grow toward ``max_slices`` when capacity
        returned (re-expansion), keep or shrink toward ``min_slices``
        when it didn't, releasing/reserving exactly the delta. Returns
        the granted size, or None when the shape cannot host even
        ``min_slices`` — the job is then moved back to the pending queue
        (the caller parks it Queued) unless the rebalance admits it off
        capacity freed in the same breath.

        Safe ONLY between attempts: the caller (TrainingJob) resizes
        exactly once per attempt, before any of that generation's pods
        exist. An unknown key/uid returns None — the caller parks
        Queued and its next reconcile's admission gate re-offers."""
        wake: List[str] = []
        granted: Optional[int] = None
        with self._lock:
            ent = self._admitted.get(key)
            if ent is not None and ent.uid == uid:
                if not self._inventory.modeled(ent.demand_key):
                    # Unmodeled shape: nothing to account against — the
                    # gang runs at its preferred size.
                    ent.slices = max_slices
                    ent.min_slices = min_slices
                    return max_slices
                avail = self._inventory.free(ent.demand_key) + ent.slices
                if avail >= min_slices:
                    new = min(max_slices, avail)
                    delta = new - ent.slices
                    if delta > 0:
                        self._inventory.reserve(ent.demand_key, delta)
                    elif delta < 0:
                        self._inventory.release(ent.demand_key, -delta)
                    ent.slices = new
                    ent.min_slices = min_slices
                    if delta < 0:
                        # A shrink freed real capacity: pending gangs
                        # may now fit.
                        wake = self._rebalance_locked()
                    granted = new
                else:
                    # Even the minimum no longer fits (the pool shrank
                    # under a parked restart): back to the queue on the
                    # normal admission order.
                    self._release_locked(ent)
                    self._seq += 1
                    self._pending[key] = _Entry(
                        key=key, uid=uid, demand_key=ent.demand_key,
                        slices=max_slices, min_slices=min_slices,
                        preferred=ent.preferred or max_slices,
                        priority=ent.priority, queue=ent.queue,
                        seq=self._seq, enqueued_at=self._clock())
                    wake = self._rebalance_locked()
                    readmitted = self._admitted.get(key)
                    granted = (readmitted.slices
                               if readmitted is not None else None)
        self._notify(wake, skip=key)
        return granted

    def peek_eviction(self, key: str,
                      uid: Optional[str] = None) -> Optional[str]:
        """Non-consuming view of a pending preemption directive: the
        drain-first eviction path reads the reason to stamp a
        cooperative drain while the directive — and the victim's
        reservation — stays in place until the drained gang's planned
        exit (or drain-deadline expiry) pops it for real. The
        in-flight-eviction credit in ``_mark_victims_locked`` keeps a
        peeked-but-unpopped victim counted toward the preemptor's
        shortfall, so the drain window cannot cascade extra victims.
        A directive recorded against a different UID targeted a deleted
        predecessor: dropped here exactly as ``pop_eviction`` would."""
        with self._lock:
            entry = self._evicting.get(key)
            if entry is None:
                return None
            marked_uid, reason = entry
            if uid is not None and marked_uid != uid:
                del self._evicting[key]
                return None
            return reason

    def grow_headroom(self, key: str, *, uid: str,
                      max_slices: int) -> Optional[int]:
        """The size ``key``'s admitted reservation could grow to right
        now (its shape's free capacity plus what it already holds,
        capped at ``max_slices``) — WITHOUT mutating anything. The
        live-resize trigger probes this from reconcile and only drains
        the gang once headroom has held through the debounce window.
        None when the job is not admitted under this UID or its shape
        is unmodeled (unmodeled gangs already run at their preferred
        size)."""
        with self._lock:
            ent = self._admitted.get(key)
            if ent is None or ent.uid != uid:
                return None
            if not self._inventory.modeled(ent.demand_key):
                return None
            return min(max_slices,
                       self._inventory.free(ent.demand_key) + ent.slices)

    def pop_eviction(self, key: str,
                     uid: Optional[str] = None) -> Optional[str]:
        """Deliver (and consume) a pending preemption directive for
        ``key``: releases the victim's reservation and rebalances — the
        waiting higher-priority job admits off the freed capacity.
        Returns the human-readable reason, or None when the job is not
        marked. ``uid`` scopes delivery: a directive recorded against a
        different UID targeted a deleted predecessor of the same name and
        is dropped, never applied to the successor. (None = match any —
        test convenience.) ``tpujob_preemptions_total`` ticks at the
        caller's actual teardown, not here: a victim whose gang already
        succeeded consumes the directive without being evicted."""
        with self._lock:
            entry = self._evicting.get(key)
            if entry is None:
                return None
            marked_uid, reason = entry
            del self._evicting[key]
            if uid is not None and marked_uid != uid:
                # Stale directive for a dead predecessor: its reservation
                # was already released when the old job went away; do not
                # touch the successor's state.
                return None
            ent = self._admitted.pop(key, None)
            if ent is not None:
                self._inventory.release(ent.demand_key, ent.slices)
            wake = self._rebalance_locked()
        self._notify(wake, skip=key)
        return reason

    def update_inventory(self, capacity: Dict[str, int]) -> None:
        """Live capacity refresh (the node-informer feed): swap the
        modeled capacity, re-examine sidelined jobs, and rebalance so
        newly-fitting gangs admit WITHOUT an operator restart.

        Un-sidelining matters: a job parked unschedulable ("demand
        exceeds total capacity") under the old model may fit the new one
        — and conversely the rebalance re-sidelines heads that now exceed
        a shrunken shape, so one drained node pool cannot head-block its
        shape forever. Reservations are preserved across the swap
        (inventory.set_capacity): a shrink below current usage is honest
        over-commit that drains as gangs finish."""
        with self._lock:
            self._inventory.set_capacity(capacity)
            for ent in self._pending.values():
                if not ent.impossible:
                    continue
                total = self._inventory.capacity(ent.demand_key)
                if total is None or ent.floor() <= total:
                    ent.impossible = False
            wake = self._rebalance_locked()
        self._notify(wake)
        log.info("fleet: slice inventory updated (%d shapes)",
                 len(capacity or {}))

    def release(self, key: str) -> None:
        """Return ``key``'s slices (teardown/TTL/terminal/suspend/deleted)
        and drop it from the queue entirely. Idempotent."""
        with self._lock:
            ent = self._admitted.pop(key, None)
            self._evicting.pop(key, None)
            self._pending.pop(key, None)
            if ent is not None:
                self._inventory.release(ent.demand_key, ent.slices)
            wake = self._rebalance_locked()
            self._update_gauges_locked()
        self._notify(wake, skip=key)

    # -- introspection ---------------------------------------------------------

    def is_admitted(self, key: str) -> bool:
        with self._lock:
            return key in self._admitted

    def unschedulable_reason(self, key: str) -> Optional[str]:
        """Why a pending job can NEVER admit as specced (None = it is
        merely waiting): surfaces 'demand exceeds total capacity' into
        status.reason instead of an indistinguishable eternal Queued."""
        with self._lock:
            ent = self._pending.get(key)
            if ent is None or not ent.impossible:
                return None
            total = self._inventory.capacity(ent.demand_key)
            return (f"demand of {ent.floor()} slice(s) of {ent.demand_key} "
                    f"exceeds the inventory's total capacity ({total})")

    def queue_position(self, key: str) -> Optional[int]:
        """0-based admission-order position of a pending job (0 = next),
        or None when it is not pending. O(pending) — called from the
        (rare) reconciles of queued jobs, not from any hot loop."""
        with self._lock:
            ent = self._pending.get(key)
            if ent is None:
                return None
            usage = self._queue_usage_locked()
            me = self._order_key_locked(ent, usage)
            return sum(1 for other in self._pending.values()
                       if other.key != key
                       and self._order_key_locked(other, usage) < me)

    def summary(self) -> Dict[str, Any]:
        """Bench/test view: counts + inventory snapshot."""
        with self._lock:
            return {
                "admitted": len(self._admitted),
                "pending": len(self._pending),
                "evicting": len(self._evicting),
                "inventory": self._inventory.snapshot(),
            }

    # -- internals (call with _lock held) --------------------------------------

    def _release_locked(self, ent: _Entry) -> None:
        self._admitted.pop(ent.key, None)
        # A directive aimed at the entry being released is moot (and must
        # never leak onto a same-name successor).
        self._evicting.pop(ent.key, None)
        self._inventory.release(ent.demand_key, ent.slices)

    def _queue_usage_locked(self) -> Dict[str, int]:
        """Slices currently admitted per fair-share queue."""
        usage: Dict[str, int] = {}
        for ent in self._admitted.values():
            usage[ent.queue] = usage.get(ent.queue, 0) + ent.slices
        return usage

    def _order_key_locked(self, ent: _Entry, usage: Dict[str, int]) -> tuple:
        """Admission order: priority desc, then the queue with the
        smallest admitted share, then FIFO."""
        return (-ent.priority, usage.get(ent.queue, 0), ent.seq)

    def _rebalance_locked(self) -> List[str]:
        """Admit pending jobs in order while they fit. An unfittable head
        blocks further admission OF ITS OWN SLICE SHAPE only (and gets a
        preemption attempt): big gangs must not be starved by small later
        arrivals of the same shape, but a full v4 pool must never park
        v5e jobs whose own pool has free slices. Returns the keys whose
        reconciles must be woken (new admissions + new victims)."""
        wake: List[str] = []
        blocked: set = set()  # demand_keys with an unfittable head
        while True:
            usage = self._queue_usage_locked()
            candidates = [e for e in self._pending.values()
                          if e.demand_key not in blocked
                          and not e.impossible]
            if not candidates:
                break
            head = min(candidates,
                       key=lambda e: self._order_key_locked(e, usage))
            # The fit test runs against the head's FLOOR (elastic jobs
            # shrink before they queue); the grant below prefers the
            # full preferred size.
            if not self._inventory.fits(head.demand_key, head.floor()):
                total = self._inventory.capacity(head.demand_key)
                if total is not None and head.floor() > total:
                    # Demand exceeds the shape's TOTAL capacity: it can
                    # NEVER fit, no victim set can change that, and head-
                    # blocking its shape would silently starve every later
                    # same-shape job off one typo'd numSlices. Sideline it
                    # (the job's status.reason says why) and keep going.
                    head.impossible = True
                    log.warning(
                        "fleet: %s demands %d slices of %s but the "
                        "inventory models only %d total — unschedulable "
                        "until capacity or the spec changes",
                        head.key, head.floor(), head.demand_key, total)
                    wake.append(head.key)
                    continue
                wake.extend(self._mark_victims_locked(head))
                blocked.add(head.demand_key)
                continue
            self._pending.pop(head.key)
            self._seq += 1
            head.admit_seq = self._seq
            if self._inventory.modeled(head.demand_key):
                # Elastic grant: the largest size in [floor, preferred]
                # that fits right now; rigid jobs (floor == preferred)
                # always take their full size. Unmodeled shapes are
                # untracked and run at the preferred size.
                head.slices = min(
                    head.slices,
                    max(head.floor(),
                        self._inventory.free(head.demand_key)))
            self._inventory.reserve(head.demand_key, head.slices)
            self._admitted[head.key] = head
            wake.append(head.key)
            if head.enqueued_at:
                waited = max(0.0, self._clock() - head.enqueued_at)
                if self._metrics is not None:
                    self._metrics.observe(
                        "tpujob_admission_latency_seconds", waited)
                window = self._queue_waits.get(head.queue)
                if window is None:
                    if len(self._queue_waits) >= QUEUE_GAUGE_CAP:
                        # Same bound as the depth gauges: drop the
                        # stalest queue's window before admitting a new
                        # queue name (FIFO by insertion is enough — a
                        # queue that admits again simply re-enters).
                        self._queue_waits.pop(
                            next(iter(self._queue_waits)))
                    window = collections.deque(maxlen=QUEUE_WAIT_SAMPLES)
                    self._queue_waits[head.queue] = window
                window.append(waited)
        self._cancel_unjustified_evictions_locked()
        self._update_gauges_locked()
        return wake

    def _cancel_unjustified_evictions_locked(self) -> None:
        """Rescind eviction directives that no pending job justifies any
        more: if the blocked head that demanded the victims was admitted
        off independently freed capacity (or deleted), tearing the
        victims down anyway would preempt healthy gangs for nothing. An
        eviction stays justified only while some still-pending job of the
        same slice shape carries a strictly higher priority."""
        for key in list(self._evicting):
            marked_uid, _reason = self._evicting[key]
            ent = self._admitted.get(key)
            if ent is None:
                continue  # released/rebuilt elsewhere; pop will no-op it
            if ent.uid != marked_uid:
                # The marked victim is gone; the same-name successor's
                # admission must not inherit its death warrant.
                del self._evicting[key]
                continue
            justified = any(p.demand_key == ent.demand_key
                            and p.priority > ent.priority
                            for p in self._pending.values())
            if not justified:
                del self._evicting[key]
                log.info("fleet: cancelling eviction of %s (capacity "
                         "freed elsewhere; no pending higher-priority "
                         "job needs it)", key)

    def _mark_victims_locked(self, head: _Entry) -> List[str]:
        """Victim selection for a blocked higher-priority head: admitted
        jobs of the same slice shape with strictly lower priority, lowest
        priority first and newest admitted first, just enough of them to
        fit the head once they drain. No sufficient set → no eviction
        (pointlessly killing jobs that cannot free enough is worse than
        waiting)."""
        # An elastic head preempts only what its FLOOR needs: it can run
        # shrunk, so evicting victims to reach its preferred size would
        # trade running gangs for capacity it can live without.
        need = head.floor() - self._inventory.free(head.demand_key)
        # Capacity already draining from in-flight evictions counts: their
        # reconciles will release it, and double-marking new victims for
        # the same shortfall would cascade evictions on every rebalance.
        need -= sum(v.slices for k, v in self._admitted.items()
                    if k in self._evicting and v.demand_key == head.demand_key)
        if need <= 0:
            return []
        # Within a priority band, a serving fleet at its replica floor
        # goes LAST: it has no slack to give back — eviction takes live
        # traffic to zero, where a fresh-checkpoint training gang merely
        # resumes (serve-at-min outranks the shrunk reading exactly
        # because a fleet scaled down to minReplicas LOOKS shrunk).
        # Among the rest, gangs already running SHRUNK (straggler shed,
        # tight admission grant) go first: they are degraded already,
        # their restart is billed to the infra budget either way, and
        # sparing a healthy full-width gang preserves strictly more
        # goodput. Newest-admitted breaks the remaining ties.
        candidates = sorted(
            (v for k, v in self._admitted.items()
             if k not in self._evicting
             and v.demand_key == head.demand_key
             and v.priority < head.priority),
            key=lambda v: (v.priority, v.serve_at_min(), not v.shrunk(),
                           -v.admit_seq))
        chosen: List[_Entry] = []
        freed = 0
        for victim in candidates:
            chosen.append(victim)
            freed += victim.slices
            if freed >= need:
                break
        if freed < need:
            return []
        for victim in chosen:
            reason = (f"preempted by higher-priority job {head.key} "
                      f"(priority {head.priority} > {victim.priority})")
            self._evicting[victim.key] = (victim.uid, reason)
            log.info("fleet: marking %s for preemption (%s)",
                     victim.key, reason)
        return [v.key for v in chosen]

    def _update_gauges_locked(self) -> None:
        if self._metrics is None:
            return
        depths: Dict[str, int] = {}
        for ent in self._pending.values():
            depths[ent.queue] = depths.get(ent.queue, 0) + 1
        self._known_queues.update(depths)
        if len(self._known_queues) > QUEUE_GAUGE_CAP:
            # Evict idle (zero-depth) queues first; their series leave the
            # registry so /metrics stays bounded under queue-name churn.
            for queue in sorted(self._known_queues - set(depths)):
                if len(self._known_queues) <= QUEUE_GAUGE_CAP:
                    break
                self._known_queues.discard(queue)
                self._metrics.remove_series("tpujob_queue_depth",
                                            labels={"queue": queue})
        for queue in self._known_queues:
            self._metrics.set_gauge("tpujob_queue_depth",
                                    depths.get(queue, 0),
                                    labels={"queue": queue})

    def queue_wait_quantiles(self) -> Dict[str, Dict[str, Any]]:
        """Recent per-queue admission-wait p50/p95 (+ sample count) for
        the fleet rollup (``GET /api/fleet``): nearest-rank over the
        newest QUEUE_WAIT_SAMPLES admissions of each queue."""
        with self._lock:
            windows = {queue: list(w)
                       for queue, w in self._queue_waits.items() if w}
        return {queue: timeline_mod.quantiles(samples)
                for queue, samples in windows.items()}

    # -- wakeups (outside the lock: enqueue takes the workqueue's lock) --------

    def _notify(self, keys: List[str], skip: str = "") -> None:
        if self._enqueue is None:
            return
        for key in keys:
            if key != skip:
                self._enqueue(key)
