"""Spec validation for TPUJob.

Reference parity: pkg/apis/mxnet/validation/validation.go:30-84
(``ValidateTFJobSpec``): termination policy + chief required (:32-34,45-47),
replica template and port non-nil (:41-51), replica type in the allowed set
(:54-66), a container with the magic name present (:68-76), and the chief
replica set must exist (:79-81).

TPU-native additions: SCHEDULER replica count must be exactly 1 (the
reference enforces this later, in the replica-set constructor,
replicas.go:87-93 — hoisted here so invalid specs fail validation instead of
reconcile), duplicate role detection, whole-group restart-policy validity,
and TPU resource-request sanity (a WORKER template requesting
``cloud-tpus.google.com/*`` must request the same count on every worker).
"""

from __future__ import annotations

from typing import List

from tpu_operator.apis.tpujob.v1alpha1.types import (
    DEFAULT_CONTAINER_NAME,
    MAX_SCHEDULING_PRIORITY,
    MIN_AUTOTUNE_WINDOW_STEPS,
    CacheMedium,
    JobMode,
    RestartPolicy,
    StoreBackend,
    StragglerPolicy,
    TPUJobSpec,
    TPUReplicaType,
)


class ValidationError(ValueError):
    """Raised when a TPUJobSpec is invalid (ref: fmt.Errorf returns)."""


def validate_tpujob_spec(spec: TPUJobSpec) -> None:
    """Raise ValidationError on the first invalid field.

    Mirrors ValidateTFJobSpec (validation.go:30-84); call after defaulting.
    """
    if spec.termination_policy is None or not spec.termination_policy.chief_replica_name:
        # ref: validation.go:32-34
        raise ValidationError("invalid termination policy: a chief replica must be specified")
    chief_name = spec.termination_policy.chief_replica_name

    if not spec.replica_specs:
        raise ValidationError("job spec must contain at least one replicaSpec")

    seen_roles: List[str] = []
    chief_found = False
    for i, r in enumerate(spec.replica_specs):
        # ref: validation.go:41-44 (TFPort non-nil)
        if r.tpu_port is None:
            raise ValidationError(f"replicaSpec[{i}]: tpuPort can't be None")
        # ref: validation.go:45-47 (chief membership check, one branch)
        if r.tpu_replica_type == chief_name:
            chief_found = True
        # ref: validation.go:48-51 (Template non-nil except legacy MASTER case;
        # no legacy case here — template is always required)
        if r.template is None:
            raise ValidationError(f"replicaSpec[{i}]: template can't be None")
        # ref: validation.go:54-66 (valid replica type)
        if r.tpu_replica_type not in TPUReplicaType.ALL:
            raise ValidationError(
                f"replicaSpec[{i}]: tpuReplicaType {r.tpu_replica_type!r} is not in "
                f"{list(TPUReplicaType.ALL)}"
            )
        if r.tpu_replica_type in seen_roles:
            raise ValidationError(
                f"replicaSpec[{i}]: duplicate replica type {r.tpu_replica_type!r}"
            )
        seen_roles.append(r.tpu_replica_type)
        # ref: replicas.go:87-93 (SCHEDULER must have exactly 1 replica) —
        # hoisted from the replica-set constructor into validation.
        if r.tpu_replica_type == TPUReplicaType.SCHEDULER and r.replicas != 1:
            raise ValidationError("the SCHEDULER replica set must have exactly 1 replica")
        if r.replicas < 1:
            raise ValidationError(f"replicaSpec[{i}]: replicas must be >= 1")

        _validate_template(i, r.template)

    if not chief_found:
        # ref: validation.go:79-81
        raise ValidationError(
            f"terminationPolicy chief replica {chief_name!r} matches no replicaSpec"
        )

    if spec.restart_policy and spec.restart_policy not in RestartPolicy.ALL:
        raise ValidationError(
            f"restartPolicy {spec.restart_policy!r} is not in {list(RestartPolicy.ALL)}"
        )
    if spec.num_slices < 1:
        raise ValidationError("numSlices must be >= 1")

    # Serving mode: serve replicas are independent decode servers behind
    # readiness-gated Services, so the mode constrains the restart and
    # sizing machinery built for training gangs.
    if spec.mode and spec.mode not in JobMode.ALL:
        raise ValidationError(
            f"mode {spec.mode!r} is not in {list(JobMode.ALL)}")
    if spec.serving is not None and spec.mode != JobMode.SERVE:
        raise ValidationError(
            "spec.serving is only meaningful under mode: serve")
    if spec.mode == JobMode.SERVE:
        worker = next((r for r in spec.replica_specs
                       if r.tpu_replica_type == TPUReplicaType.WORKER),
                      None)
        if worker is None:
            raise ValidationError("mode serve requires a WORKER replicaSpec")
        if any(r.tpu_replica_type != TPUReplicaType.WORKER
               for r in spec.replica_specs):
            # The readiness gate maps heartbeat process ids onto WORKER
            # task indices 1:1 and gates EVERY per-index Service on it; a
            # compat SCHEDULER/SERVER role would shift that mapping and
            # have its own (never-serving-beat) Service deleted. Serve
            # replicas are independent decode servers — the PS-compat
            # roles have no meaning here.
            raise ValidationError(
                "mode serve requires WORKER-only replicaSpecs "
                "(SCHEDULER/SERVER are parameter-server compat roles; "
                "serve replicas are independent decode servers)")
        if spec.restart_policy == RestartPolicy.WHOLE_GROUP:
            raise ValidationError(
                "mode serve requires restartPolicy PerPod: replicas are "
                "independent servers, and a member death restarting the "
                "whole fleet would drop every in-flight request")
        if spec.elastic is not None:
            raise ValidationError(
                "mode serve excludes spec.elastic: serving owns its "
                "replica count through spec.serving (traffic-driven "
                "scaling), and elastic sizing requires the WholeGroup "
                "gang boundary serve mode deliberately lacks")
        if spec.num_slices > 1 and worker.replicas != spec.num_slices:
            # Checked at the MODE level, not only under a serving block:
            # a serve job without one still runs independent
            # single-process servers, and replicas != numSlices would
            # desynchronize pod count from slice accounting either way.
            raise ValidationError(
                f"mode serve with numSlices > 1 requires WORKER "
                f"replicas ({worker.replicas}) == numSlices "
                f"({spec.num_slices}): each serve replica is one "
                f"independent slice server, so the scaling unit is "
                f"one slice")
        sv = spec.serving
        if sv is not None:
            if sv.min_replicas < 1:
                raise ValidationError("serving.minReplicas must be >= 1")
            if sv.max_replicas < sv.min_replicas:
                raise ValidationError(
                    "serving.maxReplicas must be >= minReplicas")
            if not (sv.min_replicas <= worker.replicas
                    <= sv.max_replicas):
                raise ValidationError(
                    f"WORKER replicas ({worker.replicas}) must lie within "
                    f"serving [minReplicas, maxReplicas] = "
                    f"[{sv.min_replicas}, {sv.max_replicas}]: the spec'd "
                    f"count is the scaling start point")
            if not (sv.target_requests_per_second_per_replica > 0):
                raise ValidationError(
                    "serving.targetRequestsPerSecondPerReplica must be > 0")
            if sv.reload_poll_seconds < 1:
                raise ValidationError(
                    "serving.reloadPollSeconds must be >= 1")
            if sv.straggler_policy not in (StragglerPolicy.NONE,
                                           StragglerPolicy.REPLACE):
                raise ValidationError(
                    f"serving.stragglerPolicy {sv.straggler_policy!r} must "
                    f"be 'none' or 'replace' (shed removes a slice from a "
                    f"gang — an elastic-training concept)")
            if sv.straggler_patience_seconds < 1:
                raise ValidationError(
                    "serving.stragglerPatienceSeconds must be >= 1")

    # Time-aware recovery fields (batch/v1 Job analogues).
    if spec.active_deadline_seconds is not None and spec.active_deadline_seconds < 1:
        raise ValidationError("activeDeadlineSeconds must be >= 1")
    if spec.stall_timeout_seconds is not None and spec.stall_timeout_seconds < 1:
        raise ValidationError("stallTimeoutSeconds must be >= 1")
    if spec.ttl_seconds_after_finished is not None and spec.ttl_seconds_after_finished < 0:
        raise ValidationError("ttlSecondsAfterFinished must be >= 0")
    if spec.restart_backoff is not None:
        bo = spec.restart_backoff
        if bo.base_seconds < 0:
            raise ValidationError("restartBackoff.baseSeconds must be >= 0")
        if bo.max_seconds < bo.base_seconds:
            raise ValidationError(
                "restartBackoff.maxSeconds must be >= baseSeconds"
            )

    # Fleet scheduling: bounded priority (a typo'd priority must not become
    # an un-preemptable monopoly) and a usable queue name (it becomes a
    # metric label and a fair-share bucket key).
    sched = spec.scheduling
    if sched is not None:
        if abs(sched.priority) > MAX_SCHEDULING_PRIORITY:
            raise ValidationError(
                f"scheduling.priority must be within "
                f"±{MAX_SCHEDULING_PRIORITY}"
            )
        if not sched.queue or len(sched.queue) > 63:
            raise ValidationError(
                "scheduling.queue must be a non-empty string of at most "
                "63 characters"
            )

    # Remote warm-start store: the URI must be present and scheme-
    # consistent with the backend, and the chunk fan-out must be a usable
    # pool size. Backends beyond the in-repo pair are allowed — they name
    # a deployment-registered factory (store/blob.register_backend), so
    # validation checks shape and consistency here and resolution is
    # gated at payload runtime with a clear error. A store block with no
    # URI is a misconfiguration, not a default — silently running
    # store-less would quietly forfeit every fresh-node warm start the
    # user asked for.
    store = spec.store
    if store is not None:
        import re as _re

        if not _re.match(StoreBackend.NAME_PATTERN, store.backend or ""):
            raise ValidationError(
                f"store.backend {store.backend!r} must match "
                f"{StoreBackend.NAME_PATTERN} (localfs, fake, or a "
                f"registered backend slug)"
            )
        if not store.uri:
            raise ValidationError(
                "store.uri is required (an absolute path / file:// URI on "
                "a pod-visible shared filesystem, fake://name in tests, or "
                "a registered backend's <scheme>://... URI)"
            )
        if store.backend == StoreBackend.LOCALFS:
            if not (store.uri.startswith("/")
                    or store.uri.startswith("file://")):
                raise ValidationError(
                    "store.uri for the localfs backend must be an absolute "
                    "path or file:// URI (it is resolved inside the pods)"
                )
        elif not store.uri.startswith(f"{store.backend}://"):
            # fake ↔ fake://, and every registered backend ↔ its scheme:
            # the payload resolves by URI scheme, so a mismatched pair
            # would silently use a different backend than spec'd.
            raise ValidationError(
                f"store.uri for the {store.backend!r} backend must be "
                f"{store.backend}://..."
            )
        if store.upload_parallelism < 1:
            raise ValidationError("store.uploadParallelism must be >= 1")
        if store.keep_snapshots < 0:
            raise ValidationError(
                "store.keepSnapshots must be >= 0 (0 = keep every "
                "verified snapshot, N = retain only the newest N)")

    # Data-plane flight recorder — validated UNCONDITIONALLY (unlike the
    # cache block): the generated CRD carries these minimums with no
    # enabled-conditional, so an enabled-only check here would admit a
    # disabled-but-invalid block everywhere the fake apiserver runs and
    # have the real apiserver reject it at the door. The ring buffer
    # needs enough samples for a p95 to mean anything, and a straggler
    # ratio below 1.0 would flag the MAJORITY of a healthy gang (every
    # member sits near the median; ratio 1.0 = flag anything
    # at-or-above median — permitted as the maximally-sensitive
    # setting, but nothing below it parses).
    trace = spec.step_trace
    if trace is not None:
        if trace.buffer_steps < 8:
            raise ValidationError(
                "stepTrace.bufferSteps must be >= 8 (the postmortem ring "
                "needs enough steps for its percentiles to mean anything)"
            )
        if trace.straggler_ratio < 1.0:
            raise ValidationError(
                "stepTrace.stragglerRatio must be >= 1.0 (below the gang "
                "median, every healthy member would be flagged)"
            )

    # Self-tuning data plane. prefetchDepth 0 = AUTO by convention (the
    # runtime resolves it; payload/autotune.resolve_prefetch_depth), so
    # only negatives are invalid; an explicit positive depth under an
    # ENABLED autotuner must sit inside the tuning range — starting the
    # hill climb outside its own clamp would either snap the depth the
    # user pinned or dead-band the controller, both silently.
    dp = spec.data_plane
    if dp is not None:
        if dp.prefetch_depth < 0:
            raise ValidationError(
                "dataPlane.prefetchDepth must be >= 0 (0 = auto)"
            )
        at = dp.autotune
        if at is not None:
            if at.min_depth < 0:
                raise ValidationError(
                    "dataPlane.autotune.minDepth must be >= 0"
                )
            if at.max_depth < max(1, at.min_depth):
                raise ValidationError(
                    f"dataPlane.autotune.maxDepth ({at.max_depth}) must "
                    f"be >= minDepth ({at.min_depth}) and >= 1"
                )
            if at.window_steps < MIN_AUTOTUNE_WINDOW_STEPS:
                raise ValidationError(
                    f"dataPlane.autotune.windowSteps must be >= "
                    f"{MIN_AUTOTUNE_WINDOW_STEPS} (a smaller window's "
                    f"phase means are noise, and the hill climb would "
                    f"chase it)"
                )
            if at.enabled and dp.prefetch_depth > 0 and not (
                    at.min_depth <= dp.prefetch_depth <= at.max_depth):
                raise ValidationError(
                    f"dataPlane.prefetchDepth ({dp.prefetch_depth}) must "
                    f"lie within autotune [minDepth, maxDepth] = "
                    f"[{at.min_depth}, {at.max_depth}] when autotune is "
                    f"enabled"
                )

    # Elastic gangs: the sizing range must be a usable sub-range of the
    # spec'd world — the worker template provisions one slice's worth of
    # processes per numSlices unit, so an attempt can gang at FEWER
    # slices than spec'd (scaling the worker count down evenly) but a
    # maxSlices past numSlices would demand processes the template never
    # provisioned. Whole-group restart semantics are required: a PerPod
    # job has no gang boundary at which a resize could be consistent.
    el = spec.elastic
    if el is not None:
        if el.min_slices < 1:
            raise ValidationError("elastic.minSlices must be >= 1")
        if el.max_slices < el.min_slices:
            raise ValidationError(
                "elastic.maxSlices must be >= minSlices"
            )
        if el.max_slices > spec.num_slices:
            raise ValidationError(
                f"elastic.maxSlices ({el.max_slices}) must be <= numSlices "
                f"({spec.num_slices}): the worker template provisions "
                f"processes for at most numSlices slices"
            )
        if spec.restart_policy and \
                spec.restart_policy != RestartPolicy.WHOLE_GROUP:
            raise ValidationError(
                "elastic sizing requires restartPolicy WholeGroup (a "
                "PerPod job has no gang boundary to resize at)"
            )
        if el.straggler_policy not in StragglerPolicy.ALL:
            raise ValidationError(
                f"elastic.stragglerPolicy {el.straggler_policy!r} is not "
                f"in {list(StragglerPolicy.ALL)}"
            )
        if el.straggler_patience_seconds < 1:
            raise ValidationError(
                "elastic.stragglerPatienceSeconds must be >= 1"
            )
        worker = next((r for r in spec.replica_specs
                       if r.tpu_replica_type == TPUReplicaType.WORKER),
                      None)
        if worker is None:
            raise ValidationError(
                "elastic sizing requires a WORKER replicaSpec")
        if worker.replicas % max(1, spec.num_slices) != 0:
            raise ValidationError(
                f"elastic sizing requires WORKER replicas "
                f"({worker.replicas}) divisible by numSlices "
                f"({spec.num_slices}) so a resized gang scales evenly"
            )

    # Cooperative drain: the deadline must be a usable window (>= 1 s —
    # zero would expire every directive before the first heartbeat ACK
    # could even carry it), the debounce merely non-negative (0 =
    # immediate grow, a legitimate choice for stable inventories).
    dr = spec.drain
    if dr is not None:
        if dr.deadline_seconds < 1:
            raise ValidationError(
                "drain.deadlineSeconds must be >= 1 (a zero deadline "
                "expires every directive before the payload can ACK it)"
            )
        if dr.resize_debounce_seconds < 0:
            raise ValidationError(
                "drain.resizeDebounceSeconds must be >= 0"
            )

    # Warm-restart compilation cache (validated only when enabled: a
    # disabled block is inert, whatever its other fields say).
    cache = spec.compilation_cache
    if cache is not None and cache.enabled:
        if cache.medium not in CacheMedium.ALL:
            raise ValidationError(
                f"compilationCache.medium {cache.medium!r} is not in "
                f"{list(CacheMedium.ALL)}"
            )
        if not cache.path or not cache.path.startswith("/"):
            raise ValidationError(
                "compilationCache.path must be an absolute path "
                "(it is both the container mount point and, for medium "
                "hostPath, the node directory)"
            )


def _validate_template(index: int, template: dict) -> None:
    """Template must contain a container named DEFAULT_CONTAINER_NAME
    (ref: validation.go:68-76 requires a container named "mxnet")."""
    pod_spec = (template or {}).get("spec") or {}
    containers = pod_spec.get("containers") or []
    if not any(c.get("name") == DEFAULT_CONTAINER_NAME for c in containers):
        raise ValidationError(
            f"replicaSpec[{index}]: template must contain a container named "
            f"{DEFAULT_CONTAINER_NAME!r}"
        )


def validate_tpu_resources(spec: TPUJobSpec) -> None:
    """TPU-native sanity: all replicas of a set share the template, so the
    per-set TPU chip request is uniform by construction; across WORKER sets
    of a multi-slice job, slice sizes must match (megascale requires equal
    slices). Called from setup after defaulting."""
    from tpu_operator.apis.tpujob.helper import tpu_chips_requested

    if spec.num_slices > 1:
        worker = next(
            (r for r in spec.replica_specs if r.tpu_replica_type == TPUReplicaType.WORKER),
            None,
        )
        if worker is None:
            raise ValidationError("multi-slice jobs require a WORKER replicaSpec")
        if worker.replicas % spec.num_slices != 0:
            raise ValidationError(
                f"WORKER replicas ({worker.replicas}) must be divisible by "
                f"numSlices ({spec.num_slices})"
            )
        if tpu_chips_requested(worker.template) == 0:
            raise ValidationError("multi-slice WORKER template requests no TPU chips")
