"""Defaulting for TPUJob specs.

Reference parity: the reference applies defaults inside ``TrainingJob.setup``
via an inline closure (pkg/trainer/training.go:229-261): replica count
defaults to 1, port defaults to 9000, replica type defaults to the job kind's
worker, and the termination policy defaults to chief = SCHEDULER replica 0
(training.go:252-257). Hoisted into a standalone, idempotent function here so
it is unit-testable on its own (the reference's closure shape made it
untestable without a full TrainingJob).
"""

from __future__ import annotations

from tpu_operator.apis.tpujob.v1alpha1.types import (
    DEFAULT_AUTOTUNE_MAX_DEPTH,
    DEFAULT_AUTOTUNE_MIN_DEPTH,
    DEFAULT_AUTOTUNE_WINDOW_STEPS,
    DEFAULT_CACHE_PATH,
    DEFAULT_DRAIN_DEADLINE_SECONDS,
    DEFAULT_RESIZE_DEBOUNCE_SECONDS,
    DEFAULT_SCHEDULING_QUEUE,
    DEFAULT_STEPTRACE_BUFFER,
    DEFAULT_STRAGGLER_RATIO,
    DEFAULT_TPU_PORT,
    DEFAULT_TPU_REPLICAS,
    CacheMedium,
    JobMode,
    RestartBackoffSpec,
    RestartPolicy,
    StoreBackend,
    TerminationPolicySpec,
    TPUJobSpec,
    TPUReplicaType,
)

# Data-plane flight recorder (``step_trace``): deliberately NO defaulting
# code — the block stays optional (None = recorder on at the defaults,
# kept absent so specs round-trip unchanged), StepTraceSpec.from_dict
# already fills absent fields from these constants, and an explicitly
# written zero/negative bufferSteps or stragglerRatio must reach
# validation.py and fail loudly (the uploadParallelism lesson: a defaults
# clamp silently masks the validation error it duplicates). The sanity
# check pins the shipped defaults inside validation's own bounds.
assert DEFAULT_STEPTRACE_BUFFER >= 8 and DEFAULT_STRAGGLER_RATIO >= 1.0

# Self-tuning data plane (``data_plane``): same discipline — the block
# stays optional (None = the static shipped config), from_dict fills
# absent fields (prefetchDepth 0 = auto by convention, never rewritten
# here so the wire round-trips what the user wrote), and explicit junk
# (minDepth > maxDepth, tiny windowSteps) reaches validation.py loudly.
assert 0 < DEFAULT_AUTOTUNE_MIN_DEPTH <= DEFAULT_AUTOTUNE_MAX_DEPTH
assert DEFAULT_AUTOTUNE_WINDOW_STEPS >= 8

# Cooperative drain (``drain``): same discipline — the block stays
# optional (None = the defaults; the protocol is always available),
# DrainSpec.from_dict fills absent fields, and an explicitly written
# zero/negative deadlineSeconds reaches validation.py loudly. The pin
# keeps the shipped defaults inside validation's own bounds.
assert DEFAULT_DRAIN_DEADLINE_SECONDS >= 1
assert DEFAULT_RESIZE_DEBOUNCE_SECONDS >= 0


def set_defaults(spec: TPUJobSpec) -> TPUJobSpec:
    """Fill unset fields in place and return the spec.

    Chief defaulting (ref: training.go:252-257): if a SCHEDULER replica set
    exists the chief is SCHEDULER[0] (compat mode); otherwise — the
    TPU-native scheduler-less case — the chief is WORKER[0], whose pod also
    hosts the jax.distributed coordinator.

    Restart-policy defaulting (TPU-native): WORKER-only jobs default to
    WHOLE_GROUP (a JAX process group cannot lose a member); specs containing
    SCHEDULER/SERVER roles default to PER_POD, matching the reference's
    per-pod recreate behavior (replicas.go:497-525).
    """
    roles = set()
    for rs in spec.replica_specs:
        if not rs.tpu_replica_type:
            rs.tpu_replica_type = TPUReplicaType.WORKER
        rs.tpu_replica_type = rs.tpu_replica_type.upper()
        roles.add(rs.tpu_replica_type)
        if not rs.replicas or rs.replicas < 1:
            rs.replicas = DEFAULT_TPU_REPLICAS
        if rs.tpu_port is None:
            rs.tpu_port = DEFAULT_TPU_PORT

    if spec.termination_policy is None:
        if TPUReplicaType.SCHEDULER in roles:
            chief = TPUReplicaType.SCHEDULER
        else:
            chief = TPUReplicaType.WORKER
        spec.termination_policy = TerminationPolicySpec(
            chief_replica_name=chief, chief_replica_index=0
        )

    # Job mode: the wire value is case-normalized; "" stays "" (absent =
    # train, kept unset so specs round-trip unchanged).
    if spec.mode:
        spec.mode = spec.mode.lower()

    if not spec.restart_policy:
        ps_mode = bool(roles & {TPUReplicaType.SCHEDULER, TPUReplicaType.SERVER})
        if spec.mode == JobMode.SERVE:
            # Serve replicas are independent decode servers: a member
            # death must restart only that member, never the fleet — the
            # opposite default from a training gang, whose JAX group
            # cannot lose a member.
            spec.restart_policy = RestartPolicy.PER_POD
        else:
            spec.restart_policy = RestartPolicy.PER_POD if ps_mode \
                else RestartPolicy.WHOLE_GROUP

    if spec.max_restarts < 0:
        spec.max_restarts = 0
    if spec.num_slices < 1:
        spec.num_slices = 1

    # Restart backoff (time-aware recovery): default to the exponential
    # 10 s → 360 s schedule; an explicit ``baseSeconds: 0`` (kept as-is)
    # opts a job out of backoff entirely.
    if spec.restart_backoff is None:
        spec.restart_backoff = RestartBackoffSpec()

    # Fleet scheduling: the block stays optional (None = priority 0 in the
    # "default" queue — the scheduler applies the same fallback, so specs
    # round-trip unchanged); a present block fills an unset/empty queue.
    if spec.scheduling is not None and not spec.scheduling.queue:
        spec.scheduling.queue = DEFAULT_SCHEDULING_QUEUE

    # Serving mode: the block stays opt-in (None = serve at the spec'd
    # replica count, no traffic scaling). A present block fills only the
    # UNSET maxReplicas from the WORKER replica count — the natural
    # ceiling when the user names none; explicitly written junk
    # (min > max, zero target) reaches validation.py and fails loudly
    # (the uploadParallelism lesson).
    if spec.serving is not None and not spec.serving.max_replicas:
        workers = sum(r.replicas for r in spec.replica_specs
                      if r.tpu_replica_type == TPUReplicaType.WORKER)
        spec.serving.max_replicas = max(workers, spec.serving.min_replicas,
                                        1)

    # Elastic gangs: the block stays opt-in (None = rigid sizing). A
    # present block fills only the UNSET maxSlices from numSlices — the
    # spec'd size is the most the worker pods provision processes for,
    # so the range can shrink from it but never grow past it. An
    # explicitly written bad minSlices/maxSlices/policy reaches
    # validation.py and fails loudly (the uploadParallelism lesson).
    if spec.elastic is not None and not spec.elastic.max_slices:
        spec.elastic.max_slices = max(1, spec.num_slices)

    # Warm-restart compilation cache: the block stays opt-in (None = off),
    # but a present block fills its unset fields — ``compilationCache: {}``
    # means "the default cache": enabled, hostPath, the standard path.
    if spec.compilation_cache is not None:
        cache = spec.compilation_cache
        if not cache.path:
            cache.path = DEFAULT_CACHE_PATH
        if not cache.medium:
            cache.medium = CacheMedium.HOSTPATH

    # Remote warm-start store: opt-in (None = off); a present block fills
    # its unset fields. The backend defaults from the URI scheme when the
    # user gave only a URI (``store: {uri: fake://t}`` means the fake
    # backend, ``gs://…`` a registered "gs" backend — never a localfs
    # path that happens to contain "://"); bare paths and file:// default
    # to localfs. ``uri`` itself is never defaulted — validation requires
    # one.
    # An explicitly invalid uploadParallelism is NOT clamped here —
    # StoreSpec.from_dict already defaults an absent field, so any < 1
    # value reaching this point was user-written and validation.py must
    # reject it loudly, like every other invalid store field.
    if spec.store is not None:
        store = spec.store
        if not store.backend:
            scheme, sep, _rest = store.uri.partition("://")
            if sep and scheme and scheme != "file":
                store.backend = scheme.lower()
            else:
                store.backend = StoreBackend.LOCALFS

    return spec
