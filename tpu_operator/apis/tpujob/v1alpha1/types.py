"""TPUJob CRD schema (group ``tpuoperator.dev``, version ``v1alpha1``).

Reference parity: pkg/apis/mxnet/v1alpha1/types.go (entire file).
The reference defines one CRD, ``MXJob`` (types.go:41-104), with replica
specs typed SCHEDULER/SERVER/WORKER (types.go:78-82), a chief-based
termination policy (types.go:65-73), job phases (types.go:106-115), job and
replica states (types.go:117-155), and an admin ``ControllerConfig`` mapping
accelerator resource names to injected volumes/env (types.go:170-196).

This file is the TPU-native re-design, not a translation:

- Replica pods request ``cloud-tpus.google.com/v*`` chips; the admin config
  maps TPU resource names to **topology env injection** (``TPUAcceleratorConfig``)
  instead of the reference's CUDA hostPath mounts (types.go:182-196).
- The default port is the JAX distributed-coordinator port (8476), replacing
  the MXNet PS-Lite port 9000 (types.go:30).
- WORKER-only ("scheduler-less") jobs are first-class: a pure JAX
  multi-controller group needs no SCHEDULER/SERVER roles. Those roles remain
  accepted for compatibility with reference-shaped specs.
- Pod templates are raw Kubernetes ``PodTemplateSpec`` dicts — we keep the
  reference's "don't hide Kubernetes" design decision
  (tf_job_design_doc.md:73).

Everything round-trips through plain dicts (``to_dict``/``from_dict``) because
the wire format is JSON against the apiserver.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# --- Constants (ref: types.go:22-32) ---------------------------------------

CRD_KIND = "TPUJob"
CRD_KIND_PLURAL = "tpujobs"
CRD_GROUP = "tpuoperator.dev"
CRD_VERSION = "v1alpha1"
CRD_API_VERSION = f"{CRD_GROUP}/{CRD_VERSION}"

# The container that receives coordinator env injection must have this name,
# mirroring the reference's requirement of a container named "mxnet"
# (validation.go:68-76, replicas.go:235-260).
DEFAULT_CONTAINER_NAME = "tpu"

# Default rendezvous port: jax.distributed coordinator (libtpu convention),
# replacing the reference's MXNet PS port 9000 (types.go:30).
DEFAULT_TPU_PORT = 8476

# Label keys stamped on every child pod/service (ref: replicas.go:120-129
# uses "fioravanzo.org=", "job_type=", "runtime_id=", "task_index=").
LABEL_GROUP_KEY = CRD_GROUP
LABEL_JOB_NAME = "job_name"
LABEL_JOB_TYPE = "job_type"
LABEL_RUNTIME_ID = "runtime_id"
LABEL_TASK_INDEX = "task_index"
LABEL_ATTEMPT = "attempt"

# TPU resource-name prefix (the analogue of "alpha.kubernetes.io/nvidia-gpu").
TPU_RESOURCE_PREFIX = "cloud-tpus.google.com/"


# --- Replica types (ref: types.go:78-87) -----------------------------------

class TPUReplicaType:
    """Roles a replica set can take.

    WORKER is the TPU-native role: every worker is one JAX process in a
    single multi-controller group. SCHEDULER and SERVER are accepted for
    compatibility with reference-shaped parameter-server specs
    (ref: types.go:78-82); in that mode the SCHEDULER doubles as the JAX
    coordinator and SERVERs join the group as ordinary processes.
    """

    SCHEDULER = "SCHEDULER"
    SERVER = "SERVER"
    WORKER = "WORKER"

    ALL = (SCHEDULER, SERVER, WORKER)


DEFAULT_TPU_REPLICAS = 1  # ref: types.go:84-87 (Replicas default 1)


# --- Phases and states (ref: types.go:106-155) ------------------------------

class TPUJobPhase:
    NONE = ""
    CREATING = "Creating"
    RUNNING = "Running"
    CLEANUP = "CleanUp"
    FAILED = "Failed"
    DONE = "Done"
    # TPU-native: spec.suspend parked the job — its generation's pods are
    # deleted (the slice is freed for other jobs), the object and its
    # services remain, and clearing the flag resumes the same attempt
    # (payloads continue from their checkpoint).
    SUSPENDED = "Suspended"
    # Time-aware recovery: the failed generation is already torn down (the
    # slice is freed immediately) but the next gang-create is parked until
    # ``status.backoffUntil`` — exponential spacing between group restarts
    # so a crash-looping payload cannot burn its whole retry budget in
    # seconds (batch/v1 Job backoff semantics, whole-group flavored).
    BACKOFF = "Backoff"
    # Fleet scheduling: the spec is valid but the cluster's slice inventory
    # cannot fit the *whole* gang yet (or the job was just preempted by a
    # higher-priority one). No pods exist; the admission queue promotes the
    # job back to Creating when capacity frees.
    QUEUED = "Queued"


class State:
    UNKNOWN = "Unknown"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


class ReplicaState:
    UNKNOWN = "Unknown"
    STARTING = "Starting"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


# --- Failure taxonomy (time-aware recovery) ----------------------------------

class FailureKind:
    """Classification of one group-restart-triggering failure, recorded in
    the ``status.failures`` ledger. Preemption-kind failures (slice
    preempted, node drained, SIGTERM/SIGKILL from outside) draw from a
    separate, larger retry budget than application crashes — a
    preemption-heavy slice must not exhaust the budget meant to stop
    genuinely crash-looping payloads (podFailurePolicy-style
    classification, batch/v1 Job)."""

    PREEMPTION = "preemption"
    APPLICATION = "application"
    STALL = "stall"
    DEADLINE = "deadline"
    # Operator-initiated cooperative restart (drain directive → verified
    # save → EXIT_PLANNED): billed like preemption (the operator chose the
    # restart, the payload did nothing wrong) and NEVER counted toward the
    # crash-loop backoff streak — a planned resize must not slow the very
    # re-gang it exists to perform.
    PLANNED = "planned"

    ALL = (PREEMPTION, APPLICATION, STALL, DEADLINE, PLANNED)


# Preemption-kind restarts get this multiple of spec.maxRestarts as their
# own budget (application/stall restarts use spec.maxRestarts directly).
# PLANNED restarts share this factor: both are operator/environment
# initiated, not payload crashes.
PREEMPTION_BUDGET_FACTOR = 4


# --- Job mode (training vs long-lived serving) -------------------------------

class JobMode:
    """What the gang runs for.

    TRAIN (the default, and what an absent ``spec.mode`` means) is the
    classic finite job: the gang steps to completion, the chief's exit 0
    rolls the job up Done. SERVE is the long-lived inference shape: each
    WORKER replica is an independent decode server (no cross-replica JAX
    process group), Services route only to replicas whose payload posted
    a ``ready`` serving beat, weights hot-reload from the remote store
    without an attempt bump, and the replica count follows the traffic
    signal within ``spec.serving`` — the job only ends by deletion,
    suspension, or payload exit."""

    TRAIN = "train"
    SERVE = "serve"

    ALL = (TRAIN, SERVE)


# Traffic target a serve replica is sized for when spec.serving names none.
DEFAULT_SERVE_TARGET_RPS = 100.0

# How often a serve replica polls the remote store for a newer verified
# snapshot (the hot-reload watch cadence).
DEFAULT_SERVE_RELOAD_POLL = 10

# Upper bound on retained status.failures entries (newest kept); the ledger
# is a postmortem aid, not an unbounded event log.
FAILURE_LEDGER_CAP = 32

# Annotation carrying the on-demand deep-profile directive (set by
# ``tpujobctl profile``): JSON ``{"id": <unique>, "steps": <N>}``.
# Reconcile admits it into ``status.profile`` (state Requested); the
# status server piggybacks the directive on a heartbeat ACK to process
# 0; the capture result folds back to Captured. Lives HERE (not in the
# trainer) because both the reconciler and the CLI speak it.
PROFILE_ANNOTATION = "tpu-operator.dev/profile-request"

# --- Cooperative drain protocol ----------------------------------------------
# status.drain lifecycle states: the controller stamps Requested, the
# directive rides process 0's heartbeat ACK until the payload's drainAck
# folds it to Acked, and the payload's verified-save-then-EXIT_PLANNED
# completes it. A deadline (armed through the DeadlineManager) expires a
# drain whose payload never ACKs or never exits — the fallback is
# exactly today's hard teardown, so a wedged payload degrades, never
# hangs.
class DrainState:
    REQUESTED = "Requested"
    ACKED = "Acked"
    COMPLETED = "Completed"
    EXPIRED = "Expired"

    ALL = (REQUESTED, ACKED, COMPLETED, EXPIRED)


# Why a drain was requested — recorded in status.drain and the
# job_planned_restarts_total{reason} metric label.
class DrainReason:
    RESIZE = "resize"
    PREEMPTION = "preemption"
    MAINTENANCE = "maintenance"

    ALL = (RESIZE, PREEMPTION, MAINTENANCE)


# Seconds a drain directive has to reach Completed before the deadline
# falls back to hard teardown (spec.drain.deadlineSeconds overrides).
DEFAULT_DRAIN_DEADLINE_SECONDS = 120

# Seconds the in-attempt grow trigger must observe sustained inventory
# headroom before draining for a live resize — a capacity flap inside
# this window must not cost a restart cycle
# (spec.drain.resizeDebounceSeconds overrides; 0 = immediate).
DEFAULT_RESIZE_DEBOUNCE_SECONDS = 30

# Restart backoff defaults (exponential, per group restart): base doubles
# each attempt, capped. Mirrors the workqueue's 10 s base and K8s Job's
# 6-minute cap.
DEFAULT_RESTART_BACKOFF_BASE = 10
DEFAULT_RESTART_BACKOFF_MAX = 360


# --- Warm-restart fast path (persistent compilation cache) -------------------

class CacheMedium:
    """Backing store of the persistent XLA compilation cache volume.

    HOSTPATH survives whole-group restarts that land on the same node (the
    common case for slice preemption: pods are recreated onto the same
    reserved topology) — restart N+1 deserializes the executables attempt N
    compiled. EMPTYDIR is the fallback for clusters that forbid hostPath:
    the cache then only serves compiles *within* one pod lifetime (grad
    accumulation microbatch recompiles, eval fns), not across restarts.
    """

    HOSTPATH = "hostPath"
    EMPTYDIR = "emptyDir"

    ALL = (HOSTPATH, EMPTYDIR)


DEFAULT_CACHE_PATH = "/var/cache/tpujob/xla"


# --- Remote warm-start store (checkpoints + compilation cache) ---------------

class StoreBackend:
    """Blob backends of the remote warm-start store.

    LOCALFS points the store at any shared-filesystem mount (NFS,
    Filestore, a gcsfuse mount) — the URI is an absolute path or
    ``file://`` URI visible inside the pods. FAKE is the in-process test
    backend (``fake://name``). Any OTHER slug names a deployment-
    registered backend (``tpu_operator.store.blob.register_backend`` —
    cloud SDK wrappers the images deliberately don't vendor); validation
    then requires the URI scheme to match the backend name (``backend:
    gs`` ↔ ``gs://…``), and resolution is gated at payload runtime with
    a clear error when no factory was registered.
    """

    LOCALFS = "localfs"
    FAKE = "fake"

    # The in-repo backends; NOT an exhaustive enum — see class docstring.
    ALL = (LOCALFS, FAKE)

    # Backend slugs (and registered URI schemes) must match this.
    NAME_PATTERN = r"^[a-z][a-z0-9-]{0,31}$"


DEFAULT_STORE_UPLOAD_PARALLELISM = 4

# Remote-snapshot retention: how many newest verified snapshots the
# write-behind worker keeps per job (0 = keep everything, the pre-GC
# behavior). Older steps are condemned-then-deleted after each commit —
# marker-first, so a half-deleted snapshot never looks healthy to a
# fresh-node prefetch or the serve-mode hot-reload watcher.
DEFAULT_STORE_KEEP_SNAPSHOTS = 0


# --- Self-tuning data plane (adaptive prefetch + autotune) --------------------

# ONE definition with the runtime (payload/autotune.py is stdlib-only;
# schema.py already imports its ADJUSTMENT_KEYS the same direction):
# the depth ``dataPlane.prefetchDepth: 0`` (auto) resolves to, and the
# autotune bounds/window defaults ``from_dict`` fills — the spec layer
# and the env-driven runtime cannot drift apart.
from tpu_operator.payload.autotune import (  # noqa: E402
    MIN_WINDOW_STEPS as MIN_AUTOTUNE_WINDOW_STEPS,
    DEFAULT_MAX_DEPTH as DEFAULT_AUTOTUNE_MAX_DEPTH,
    DEFAULT_MIN_DEPTH as DEFAULT_AUTOTUNE_MIN_DEPTH,
    DEFAULT_PREFETCH_DEPTH as DEFAULT_DATAPLANE_PREFETCH_DEPTH,
    DEFAULT_WINDOW_STEPS as DEFAULT_AUTOTUNE_WINDOW_STEPS,
)


# --- Data-plane flight recorder (step phase timing + straggler policy) -------

# Ring-buffer capacity default: last N steps retained for the postmortem
# artifact (payload/steptrace.py DEFAULT_BUFFER_STEPS mirrors this; the
# payload module is the runtime home, this is the spec default).
DEFAULT_STEPTRACE_BUFFER = 512

# Straggler flagging threshold: a gang member whose p95 step time exceeds
# the gang median by this ratio is flagged into status.stragglers.
DEFAULT_STRAGGLER_RATIO = 2.0


# --- Elastic gangs (inventory-sized attempts + straggler remediation) --------

class StragglerPolicy:
    """What the operator does when ``status.stragglers`` flags the same
    (attempt, process) past ``spec.elastic.stragglerPatienceSeconds``.

    NONE keeps the PR-9 behavior: flag, event, gauge — a human decides.
    REPLACE deletes the flagged member's pod (recording its node so the
    replacement avoids it) and re-creates the member into the SAME
    rendezvous under the same attempt — no restart budget is spent.
    SHED triggers a whole-group restart at the current world size minus
    one slice, billed to the preemption budget (never the crash-loop
    budget): a persistently slow host caps goodput harder than a
    slightly smaller gang does.
    """

    NONE = "none"
    REPLACE = "replace"
    SHED = "shed"

    ALL = (NONE, REPLACE, SHED)


# How long the SAME (attempt, process) must stay flagged in
# status.stragglers before a non-none stragglerPolicy acts on it — long
# enough that a transient host hiccup (GC pause, log rotation) never
# costs a pod.
DEFAULT_STRAGGLER_PATIENCE = 300

# Bound on retained status.elastic.remediations entries (newest kept) —
# an audit trail, not an unbounded event log (the FAILURE_LEDGER_CAP
# discipline).
ELASTIC_REMEDIATION_CAP = 16


# --- Fleet scheduling (admission queue + priority preemption) ----------------

# Fair-share queue a job lands in when spec.scheduling names none.
DEFAULT_SCHEDULING_QUEUE = "default"

# Priority bound (|priority| <= this): wide enough for any tiering scheme,
# finite so a typo'd priority cannot become an un-preemptable monopoly.
MAX_SCHEDULING_PRIORITY = 1_000_000


# --- Restart / gang policy (TPU-native addition) ----------------------------

class RestartPolicy:
    """Group-level restart semantics.

    The reference delegates restart to each pod's own ``restartPolicy`` and
    recreates fully-failed pods one at a time (replicas.go:497-525). A JAX
    multi-controller group cannot survive a single member dying — any process
    loss requires restarting the whole group (SURVEY.md §5 failure notes).
    ``WHOLE_GROUP`` (the default for WORKER-only jobs) therefore tears down
    and recreates every replica on a retryable failure, bumping the attempt
    counter; ``PER_POD`` preserves the reference behavior for compat specs.
    """

    WHOLE_GROUP = "WholeGroup"
    PER_POD = "PerPod"

    ALL = (WHOLE_GROUP, PER_POD)


# --- Spec types -------------------------------------------------------------

@dataclass
class TerminationPolicySpec:
    """Which replica decides job completion (ref: types.go:65-76)."""

    chief_replica_name: str = TPUReplicaType.WORKER
    chief_replica_index: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "chief": {
                "replicaName": self.chief_replica_name,
                "replicaIndex": self.chief_replica_index,
            }
        }

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["TerminationPolicySpec"]:
        if not d or "chief" not in d:
            return None
        chief = d["chief"] or {}
        return cls(
            chief_replica_name=chief.get("replicaName", TPUReplicaType.WORKER),
            chief_replica_index=int(chief.get("replicaIndex", 0)),
        )


@dataclass
class RestartBackoffSpec:
    """Exponential spacing between whole-group restarts: restart N waits
    ``min(baseSeconds * 2**(N-1), maxSeconds)`` in phase Backoff before the
    next gang-create (teardown is immediate — the slice frees right away).
    ``baseSeconds: 0`` disables backoff (instant re-gang, the pre-backoff
    behavior)."""

    base_seconds: int = DEFAULT_RESTART_BACKOFF_BASE
    max_seconds: int = DEFAULT_RESTART_BACKOFF_MAX

    def to_dict(self) -> Dict[str, Any]:
        return {"baseSeconds": self.base_seconds,
                "maxSeconds": self.max_seconds}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]
                  ) -> Optional["RestartBackoffSpec"]:
        if d is None:
            return None
        # A defaulted field must never contradict an explicit one, or
        # validation would fail the job over a field the user never wrote:
        # an omitted base caps at an explicit small max, and an omitted max
        # floors at an explicit large base.
        base_default = DEFAULT_RESTART_BACKOFF_BASE
        if d.get("maxSeconds") is not None:
            base_default = min(base_default, int(d["maxSeconds"]))
        base = int(d.get("baseSeconds", base_default))
        max_default = max(base, DEFAULT_RESTART_BACKOFF_MAX)
        return cls(
            base_seconds=base,
            max_seconds=int(d.get("maxSeconds", max_default)),
        )

    def delay_for_restart(self, n: int) -> float:
        """Backoff before restart ``n`` (1-indexed)."""
        if self.base_seconds <= 0 or n < 1:
            return 0.0
        return float(min(self.base_seconds * (2 ** (n - 1)),
                         self.max_seconds))


@dataclass
class CompilationCacheSpec:
    """Persistent XLA compilation-cache wiring (``spec.compilationCache``).

    When present and enabled, the operator mounts a cache volume (medium
    hostPath or emptyDir) at ``path`` in the ``tpu`` container and injects
    ``JAX_COMPILATION_CACHE_DIR`` + ``TPUJOB_CACHE_*``, so a restarted
    attempt deserializes the executables the previous attempt compiled
    instead of paying full XLA recompilation — the dominant cost of
    time-to-first-step on real payloads. Strictly best-effort on the
    payload side (bootstrap.enable_compilation_cache): a corrupt or
    unwritable cache dir logs and proceeds cold, never fails the attempt.
    """

    enabled: bool = True
    path: str = DEFAULT_CACHE_PATH
    medium: str = CacheMedium.HOSTPATH

    def to_dict(self) -> Dict[str, Any]:
        return {"enabled": self.enabled, "path": self.path,
                "medium": self.medium}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]
                  ) -> Optional["CompilationCacheSpec"]:
        if d is None:
            return None
        return cls(
            enabled=bool(d.get("enabled", True)),
            path=str(d.get("path", DEFAULT_CACHE_PATH)),
            medium=str(d.get("medium", CacheMedium.HOSTPATH)),
        )


@dataclass
class StoreSpec:
    """Remote warm-start store wiring (``spec.store``).

    When present, the operator injects ``TPUJOB_STORE_*`` so payloads (a)
    write-behind every verified checkpoint (and new compilation-cache
    entries) to the remote blob store without ever blocking the step
    loop, and (b) *prefetch* the newest healthy checkpoint + the compiled
    executables during the rendezvous/DNS wait — so a whole-group restart
    landing on a FRESH node (the normal outcome of fleet-scheduler
    preemption) still warm-starts instead of paying a cold compile and a
    cold (or step-0) restore.

    ``uri`` must be reachable from inside the pods: an absolute path /
    ``file://`` URI on a volume the user template mounts (backend
    ``localfs``), or ``fake://name`` for tests. ``uploadParallelism``
    bounds the chunk-transfer fan-out; ``prefetch: false`` keeps the
    write-behind but skips the startup download (upload-only mirroring).
    """

    backend: str = StoreBackend.LOCALFS
    uri: str = ""
    upload_parallelism: int = DEFAULT_STORE_UPLOAD_PARALLELISM
    prefetch: bool = True
    # Retention GC: keep only the newest N verified snapshots remotely
    # (0 = keep everything). Enforced by the write-behind worker after
    # each commit — condemn-then-delete, marker-first — so the serve-mode
    # hot-reload watcher never walks an unbounded snapshot tree.
    keep_snapshots: int = DEFAULT_STORE_KEEP_SNAPSHOTS

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"backend": self.backend, "uri": self.uri,
                             "uploadParallelism": self.upload_parallelism,
                             "prefetch": self.prefetch}
        if self.keep_snapshots:
            d["keepSnapshots"] = self.keep_snapshots
        return d

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]
                  ) -> Optional["StoreSpec"]:
        if d is None:
            return None
        return cls(
            backend=str(d.get("backend", StoreBackend.LOCALFS)),
            uri=str(d.get("uri", "")),
            upload_parallelism=int(d.get("uploadParallelism",
                                         DEFAULT_STORE_UPLOAD_PARALLELISM)),
            prefetch=bool(d.get("prefetch", True)),
            keep_snapshots=int(d.get("keepSnapshots",
                                     DEFAULT_STORE_KEEP_SNAPSHOTS)),
        )


@dataclass
class AutotuneSpec:
    """Closed-loop tuning knobs (``spec.dataPlane.autotune``).

    When enabled, the payload's controller (payload/autotune.py) reads
    the flight recorder's per-step phase digests every ``windowSteps``
    steps and hill-climbs the live data-plane knobs with hysteresis —
    prefetch depth within ``[minDepth, maxDepth]``, the async host path,
    and checkpoint cadence (coarsening only, bounded) — converging
    toward minimal non-COMPUTE residue and backing a change out when the
    next window shows regression.
    """

    enabled: bool = True
    min_depth: int = DEFAULT_AUTOTUNE_MIN_DEPTH
    max_depth: int = DEFAULT_AUTOTUNE_MAX_DEPTH
    window_steps: int = DEFAULT_AUTOTUNE_WINDOW_STEPS

    def to_dict(self) -> Dict[str, Any]:
        return {"enabled": self.enabled, "minDepth": self.min_depth,
                "maxDepth": self.max_depth,
                "windowSteps": self.window_steps}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]
                  ) -> Optional["AutotuneSpec"]:
        if d is None:
            return None
        return cls(
            enabled=bool(d.get("enabled", True)),
            min_depth=int(d.get("minDepth", DEFAULT_AUTOTUNE_MIN_DEPTH)),
            max_depth=int(d.get("maxDepth", DEFAULT_AUTOTUNE_MAX_DEPTH)),
            window_steps=int(d.get("windowSteps",
                                   DEFAULT_AUTOTUNE_WINDOW_STEPS)),
        )


@dataclass
class DataPlaneSpec:
    """Self-tuning data plane (``spec.dataPlane``).

    ``prefetchDepth`` is the input pipeline's in-flight batch window:
    ``0`` (the default) means AUTO — the runtime starts at the shipped
    default and, with ``autotune`` enabled, tunes it live per job; a
    positive value pins a static depth (settable without autotune). The
    block's presence also turns on the background host pipeline thread
    (batch generation runs ahead of consumption instead of serialized
    into the step's DATA phase). Knob state rides the heartbeat into
    ``status.dataPlane``, the ``job_prefetch_depth`` gauge, and the
    ``job_autotune_adjustments_total{knob,direction}`` counters.
    """

    # 0 = auto (runtime-resolved; tuned live when autotune is enabled).
    prefetch_depth: int = 0
    autotune: Optional[AutotuneSpec] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"prefetchDepth": self.prefetch_depth}
        if self.autotune is not None:
            d["autotune"] = self.autotune.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]
                  ) -> Optional["DataPlaneSpec"]:
        if d is None:
            return None
        return cls(
            prefetch_depth=int(d.get("prefetchDepth", 0)),
            autotune=AutotuneSpec.from_dict(d.get("autotune")),
        )


@dataclass
class StepTraceSpec:
    """Data-plane flight-recorder knobs (``spec.stepTrace``).

    The recorder itself is ON by default (it costs timestamps only — see
    payload/steptrace.py); this block tunes it. ``enabled: false`` opts
    the job's payloads out entirely. ``bufferSteps`` sizes the per-process
    ring buffer the postmortem artifact dumps (last N steps' phase
    timings). ``stragglerRatio`` is the controller-side flagging
    threshold: a gang member whose p95 step time exceeds the gang median
    by this ratio lands in ``status.stragglers`` (+ a StragglerDetected
    event) — the eviction/replace signal for operators and the fleet
    scheduler.
    """

    enabled: bool = True
    buffer_steps: int = DEFAULT_STEPTRACE_BUFFER
    straggler_ratio: float = DEFAULT_STRAGGLER_RATIO

    def to_dict(self) -> Dict[str, Any]:
        return {"enabled": self.enabled, "bufferSteps": self.buffer_steps,
                "stragglerRatio": self.straggler_ratio}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]
                  ) -> Optional["StepTraceSpec"]:
        if d is None:
            return None
        return cls(
            enabled=bool(d.get("enabled", True)),
            buffer_steps=int(d.get("bufferSteps", DEFAULT_STEPTRACE_BUFFER)),
            straggler_ratio=float(d.get("stragglerRatio",
                                        DEFAULT_STRAGGLER_RATIO)),
        )


@dataclass
class SchedulingSpec:
    """Fleet-scheduler knobs (``spec.scheduling``).

    ``priority``: higher admits first; when a higher-priority job cannot
    fit the slice inventory, the scheduler may preempt the lowest-priority
    newest-admitted job (the victim's restart bills the preemption-kind
    budget and the victim re-queues, it does not burn crash-loop budget).
    ``queue``: fair-share bucket — at equal priority, admission favors the
    queue currently holding the smallest share of the inventory, so one
    tenant flooding the cluster cannot starve the others.
    """

    priority: int = 0
    queue: str = DEFAULT_SCHEDULING_QUEUE

    def to_dict(self) -> Dict[str, Any]:
        return {"priority": self.priority, "queue": self.queue}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]
                  ) -> Optional["SchedulingSpec"]:
        if d is None:
            return None
        return cls(
            priority=int(d.get("priority", 0)),
            queue=str(d.get("queue", DEFAULT_SCHEDULING_QUEUE)),
        )


@dataclass
class DrainSpec:
    """Cooperative-drain knobs (``spec.drain``).

    ``deadlineSeconds`` bounds every drain directive: a payload that
    neither ACKs nor exits within it is hard-killed exactly like the
    pre-drain behavior (the protocol can only *improve* on hard
    teardown, never hang behind it). ``resizeDebounceSeconds`` gates the
    in-attempt grow trigger: inventory headroom must hold continuously
    for this long before a Running elastic gang is drained to re-gang
    larger — a node flap must not cost a restart cycle. Absent block =
    the defaults; the protocol itself is always on.
    """

    deadline_seconds: int = DEFAULT_DRAIN_DEADLINE_SECONDS
    resize_debounce_seconds: int = DEFAULT_RESIZE_DEBOUNCE_SECONDS

    def to_dict(self) -> Dict[str, Any]:
        return {"deadlineSeconds": self.deadline_seconds,
                "resizeDebounceSeconds": self.resize_debounce_seconds}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]
                  ) -> Optional["DrainSpec"]:
        if d is None:
            return None
        return cls(
            deadline_seconds=int(d.get("deadlineSeconds",
                                       DEFAULT_DRAIN_DEADLINE_SECONDS)),
            resize_debounce_seconds=int(
                d.get("resizeDebounceSeconds",
                      DEFAULT_RESIZE_DEBOUNCE_SECONDS)),
        )


@dataclass
class ElasticSpec:
    """Elastic gang sizing (``spec.elastic``).

    A non-elastic job's world size is immutable: a restart re-gangs
    exactly ``spec.numSlices`` slices or parks in Queued — a shrunken
    slice pool turns a recoverable preemption into indefinite queue
    wait. With this block, each gang (re)create asks the fleet scheduler
    for the LARGEST admissible world size in ``[minSlices, maxSlices]``
    from the live inventory — preferring ``maxSlices``, shrinking
    instead of queueing, and re-expanding on a later restart when
    capacity returns. ``maxSlices`` defaults to ``spec.numSlices`` (the
    worker template provisions one slice's worth of processes per
    ``numSlices`` unit, so the range can only shrink from the spec'd
    size, never grow past it). The chosen size per attempt is recorded
    in ``status.elastic`` and the failure ledger; env injection
    (``TPU_WORKER_HOSTNAMES``, ``JAX_NUM_PROCESSES``, ``MEGASCALE_*``)
    regenerates for the attempt's ACTUAL size. Checkpoints reshard
    across sizes on restore (payload/checkpoint.py).

    ``stragglerPolicy``/``stragglerPatienceSeconds``: see
    :class:`StragglerPolicy` — what to do about a member that
    ``status.stragglers`` keeps flagging.
    """

    min_slices: int = 1
    # 0 = unset → defaulted to spec.numSlices (set_defaults).
    max_slices: int = 0
    straggler_policy: str = StragglerPolicy.NONE
    straggler_patience_seconds: int = DEFAULT_STRAGGLER_PATIENCE

    def to_dict(self) -> Dict[str, Any]:
        return {"minSlices": self.min_slices,
                "maxSlices": self.max_slices,
                "stragglerPolicy": self.straggler_policy,
                "stragglerPatienceSeconds": self.straggler_patience_seconds}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]
                  ) -> Optional["ElasticSpec"]:
        if d is None:
            return None
        return cls(
            min_slices=int(d.get("minSlices", 1)),
            max_slices=int(d.get("maxSlices", 0)),
            straggler_policy=str(d.get("stragglerPolicy",
                                       StragglerPolicy.NONE)),
            straggler_patience_seconds=int(
                d.get("stragglerPatienceSeconds",
                      DEFAULT_STRAGGLER_PATIENCE)),
        )


@dataclass
class ServingSpec:
    """Serving-mode scaling + tail-latency policy (``spec.serving``,
    meaningful only under ``spec.mode: serve``).

    The controller reads the gang's aggregate requests/sec from serving
    heartbeats, computes a desired replica count within
    ``[minReplicas, maxReplicas]`` sized for
    ``targetRequestsPerSecondPerReplica``, and admits the delta through
    the fleet scheduler's queue (slice-per-replica jobs renegotiate
    their reservation exactly like an elastic resize — but with NO
    attempt bump and no gang restart: serve replicas are independent).
    ``reloadPollSeconds`` is the payload-side hot-reload watch cadence
    (how often each replica polls the remote store for a newer verified
    snapshot). ``stragglerPolicy`` routes the PR-9 straggler detector's
    tail-latency flags into the PR-10 ``replace`` remediation path
    (``shed`` is an elastic-gang concept and is not valid here)."""

    min_replicas: int = 1
    # 0 = unset → defaulted to the WORKER replica count (set_defaults).
    max_replicas: int = 0
    target_requests_per_second_per_replica: float = DEFAULT_SERVE_TARGET_RPS
    reload_poll_seconds: int = DEFAULT_SERVE_RELOAD_POLL
    straggler_policy: str = StragglerPolicy.NONE
    straggler_patience_seconds: int = DEFAULT_STRAGGLER_PATIENCE

    def to_dict(self) -> Dict[str, Any]:
        return {"minReplicas": self.min_replicas,
                "maxReplicas": self.max_replicas,
                "targetRequestsPerSecondPerReplica":
                    self.target_requests_per_second_per_replica,
                "reloadPollSeconds": self.reload_poll_seconds,
                "stragglerPolicy": self.straggler_policy,
                "stragglerPatienceSeconds":
                    self.straggler_patience_seconds}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]
                  ) -> Optional["ServingSpec"]:
        if d is None:
            return None
        return cls(
            min_replicas=int(d.get("minReplicas", 1)),
            max_replicas=int(d.get("maxReplicas", 0)),
            target_requests_per_second_per_replica=float(
                d.get("targetRequestsPerSecondPerReplica",
                      DEFAULT_SERVE_TARGET_RPS)),
            reload_poll_seconds=int(d.get("reloadPollSeconds",
                                          DEFAULT_SERVE_RELOAD_POLL)),
            straggler_policy=str(d.get("stragglerPolicy",
                                       StragglerPolicy.NONE)),
            straggler_patience_seconds=int(
                d.get("stragglerPatienceSeconds",
                      DEFAULT_STRAGGLER_PATIENCE)),
        )


@dataclass
class TPUReplicaSpec:
    """One replica set: N pods of one role (ref: types.go:93-104).

    ``template`` is a raw Kubernetes PodTemplateSpec dict, passed through to
    created pods (the reference embeds v1.PodTemplateSpec the same way).
    ``tpu_port`` is the rendezvous port the coordinator listens on.
    """

    replicas: int = DEFAULT_TPU_REPLICAS
    template: Optional[Dict[str, Any]] = None
    tpu_port: Optional[int] = DEFAULT_TPU_PORT
    tpu_replica_type: str = TPUReplicaType.WORKER

    def to_dict(self) -> Dict[str, Any]:
        return {
            "replicas": self.replicas,
            "template": self.template,
            "tpuPort": self.tpu_port,
            "tpuReplicaType": self.tpu_replica_type,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUReplicaSpec":
        # An explicit ``tpuPort: null`` on the wire is kept as None so
        # defaulting (set_defaults) and validation see what the user wrote.
        port = d["tpuPort"] if "tpuPort" in d else DEFAULT_TPU_PORT
        return cls(
            replicas=int(d.get("replicas", DEFAULT_TPU_REPLICAS)),
            template=copy.deepcopy(d.get("template")),
            tpu_port=port,
            tpu_replica_type=str(d.get("tpuReplicaType", TPUReplicaType.WORKER)),
        )


@dataclass
class TPUJobSpec:
    """Job spec (ref: types.go:54-63).

    ``runtime_id`` is generated once at setup and persisted so child-resource
    names stay stable across operator restarts (ref: training.go:272-274).
    ``scheduler_name`` passes through to pods (ref: types.go:61-62 →
    replicas.go:178). ``restart_policy`` and ``max_restarts`` are TPU-native
    additions for whole-group restart semantics.
    """

    replica_specs: List[TPUReplicaSpec] = field(default_factory=list)
    termination_policy: Optional[TerminationPolicySpec] = None
    runtime_id: str = ""
    scheduler_name: str = ""
    restart_policy: str = ""
    max_restarts: int = 3
    # TPU slice topology hint, e.g. "2x2x4" for v4-32; injected as
    # TPU_TOPOLOGY when set (multislice jobs also get MEGASCALE_* vars).
    tpu_topology: str = ""
    num_slices: int = 1
    # Checkpoint directory (a path on a PodTemplate-mounted volume). When
    # set, injected as TPU_CHECKPOINT_DIR so payloads save/restore through
    # whole-group restarts. The reference left checkpointing entirely to
    # user containers (README.md:168-180 azureFile volumes); on TPU the
    # whole-group restart semantics make operator-advertised resume
    # first-class.
    checkpoint_dir: str = ""
    # Profiler output directory; when set, injected as TPU_PROFILE_DIR
    # so payloads capture a jax.profiler steady-state trace
    # (train.train_loop) without per-job flag plumbing.
    profile_dir: str = ""
    # Suspend (batch/v1 Job semantics, Kueue-style slice management): true
    # parks the job — pods of the current attempt are deleted so the TPU
    # slice frees for other work; false resumes the same attempt (retry
    # budget untouched; checkpointed payloads continue where they stopped).
    suspend: bool = False
    # Time-aware recovery (batch/v1 Job analogues). All wall-clock driven;
    # enforcement is exact-time via the controller's deadline manager, not
    # resync-granularity.
    # Hard cap on total job wall time measured from the first entry into
    # phase Creating; exceeding it fails the job with DeadlineExceeded.
    active_deadline_seconds: Optional[int] = None
    # Hung-payload watchdog: while Running, if neither a heartbeat nor a
    # phase transition happened in this many seconds, the whole group is
    # restarted with reason StallDetected. Only set this on jobs whose
    # payload posts heartbeats (TPUJOB_STATUS_URL) — a silent payload is
    # indistinguishable from a hung one.
    stall_timeout_seconds: Optional[int] = None
    # Exponential spacing between whole-group restarts (None → defaulted).
    restart_backoff: Optional[RestartBackoffSpec] = None
    # Once the job is Done/Failed for this many seconds, the operator
    # deletes the TPUJob (children follow via OwnerReferences / explicit
    # teardown) — batch/v1 ttlSecondsAfterFinished.
    ttl_seconds_after_finished: Optional[int] = None
    # Warm-restart fast path: persistent XLA compilation cache volume + env
    # (None = off; restarts pay full recompilation, the pre-PR-5 behavior).
    compilation_cache: Optional[CompilationCacheSpec] = None
    # Fleet scheduling: admission priority + fair-share queue (None = the
    # defaults, priority 0 in the "default" queue — kept absent so specs
    # round-trip unchanged).
    scheduling: Optional[SchedulingSpec] = None
    # Remote warm-start store: write-behind checkpoint/cache uploads plus
    # rendezvous-overlapped prefetch, so cross-node restarts stay warm
    # (None = off; restarts only warm-start on the same node, the
    # pre-store behavior).
    store: Optional[StoreSpec] = None
    # Data-plane flight recorder: per-step phase timing ring buffer +
    # straggler threshold (None = the defaults — recorder on, ratio 2.0;
    # kept absent so specs round-trip unchanged).
    step_trace: Optional[StepTraceSpec] = None
    # Self-tuning data plane: adaptive prefetch depth + the closed-loop
    # autotuner over the flight recorder's phase digests (None = the
    # static shipped config, the pre-dataplane behavior).
    data_plane: Optional[DataPlaneSpec] = None
    # Elastic gangs: each attempt's world size is picked from the live
    # slice inventory within [minSlices, maxSlices] instead of being
    # pinned to numSlices, and persistently flagged stragglers are
    # replaced or shed per stragglerPolicy (None = rigid sizing, the
    # pre-elastic behavior).
    elastic: Optional[ElasticSpec] = None
    # Cooperative drain protocol knobs: per-directive deadline and the
    # in-attempt grow-trigger debounce (None = the defaults; the
    # protocol itself is always available).
    drain: Optional[DrainSpec] = None
    # Job mode: "" / "train" = the classic finite training job; "serve" =
    # long-lived inference gang (readiness-gated Services, hot weight
    # reload from the remote store, traffic-driven replica scaling).
    mode: str = ""
    # Serving-mode scaling + tail-latency policy (mode: serve only;
    # None = the defaults — serve at the spec'd replica count).
    serving: Optional[ServingSpec] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "replicaSpecs": [r.to_dict() for r in self.replica_specs],
        }
        if self.termination_policy is not None:
            d["terminationPolicy"] = self.termination_policy.to_dict()
        if self.runtime_id:
            d["runtimeId"] = self.runtime_id
        if self.scheduler_name:
            d["schedulerName"] = self.scheduler_name
        if self.restart_policy:
            d["restartPolicy"] = self.restart_policy
        d["maxRestarts"] = self.max_restarts
        if self.tpu_topology:
            d["tpuTopology"] = self.tpu_topology
        if self.num_slices != 1:
            d["numSlices"] = self.num_slices
        if self.checkpoint_dir:
            d["checkpointDir"] = self.checkpoint_dir
        if self.profile_dir:
            d["profileDir"] = self.profile_dir
        if self.suspend:
            d["suspend"] = True
        if self.active_deadline_seconds is not None:
            d["activeDeadlineSeconds"] = self.active_deadline_seconds
        if self.stall_timeout_seconds is not None:
            d["stallTimeoutSeconds"] = self.stall_timeout_seconds
        if self.restart_backoff is not None:
            d["restartBackoff"] = self.restart_backoff.to_dict()
        if self.ttl_seconds_after_finished is not None:
            d["ttlSecondsAfterFinished"] = self.ttl_seconds_after_finished
        if self.compilation_cache is not None:
            d["compilationCache"] = self.compilation_cache.to_dict()
        if self.scheduling is not None:
            d["scheduling"] = self.scheduling.to_dict()
        if self.store is not None:
            d["store"] = self.store.to_dict()
        if self.step_trace is not None:
            d["stepTrace"] = self.step_trace.to_dict()
        if self.data_plane is not None:
            d["dataPlane"] = self.data_plane.to_dict()
        if self.elastic is not None:
            d["elastic"] = self.elastic.to_dict()
        if self.drain is not None:
            d["drain"] = self.drain.to_dict()
        if self.mode:
            d["mode"] = self.mode
        if self.serving is not None:
            d["serving"] = self.serving.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUJobSpec":
        def opt_int(key: str) -> Optional[int]:
            return int(d[key]) if d.get(key) is not None else None

        return cls(
            replica_specs=[TPUReplicaSpec.from_dict(r) for r in d.get("replicaSpecs", [])],
            termination_policy=TerminationPolicySpec.from_dict(d.get("terminationPolicy")),
            runtime_id=str(d.get("runtimeId", "")),
            scheduler_name=str(d.get("schedulerName", "")),
            restart_policy=str(d.get("restartPolicy", "")),
            max_restarts=int(d.get("maxRestarts", 3)),
            tpu_topology=str(d.get("tpuTopology", "")),
            num_slices=int(d.get("numSlices", 1)),
            checkpoint_dir=str(d.get("checkpointDir", "")),
            profile_dir=str(d.get("profileDir", "")),
            suspend=bool(d.get("suspend", False)),
            active_deadline_seconds=opt_int("activeDeadlineSeconds"),
            stall_timeout_seconds=opt_int("stallTimeoutSeconds"),
            restart_backoff=RestartBackoffSpec.from_dict(
                d.get("restartBackoff")),
            ttl_seconds_after_finished=opt_int("ttlSecondsAfterFinished"),
            compilation_cache=CompilationCacheSpec.from_dict(
                d.get("compilationCache")),
            scheduling=SchedulingSpec.from_dict(d.get("scheduling")),
            store=StoreSpec.from_dict(d.get("store")),
            step_trace=StepTraceSpec.from_dict(d.get("stepTrace")),
            data_plane=DataPlaneSpec.from_dict(d.get("dataPlane")),
            elastic=ElasticSpec.from_dict(d.get("elastic")),
            drain=DrainSpec.from_dict(d.get("drain")),
            mode=str(d.get("mode", "")),
            serving=ServingSpec.from_dict(d.get("serving")),
        )


# --- Status types (ref: types.go:117-155) -----------------------------------

@dataclass
class TPUReplicaStatus:
    """Status of one replica set (ref: types.go:137-149)."""

    tpu_replica_type: str = TPUReplicaType.WORKER
    state: str = ReplicaState.UNKNOWN
    # Map of ReplicaState -> count of replicas in that state.
    replicas_states: Dict[str, int] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "tpuReplicaType": self.tpu_replica_type,
            "state": self.state,
            "replicasStates": dict(self.replicas_states),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUReplicaStatus":
        return cls(
            tpu_replica_type=str(d.get("tpuReplicaType", TPUReplicaType.WORKER)),
            state=str(d.get("state", ReplicaState.UNKNOWN)),
            replicas_states={str(k): int(v) for k, v in (d.get("replicasStates") or {}).items()},
        )


@dataclass
class FailureRecord:
    """One entry of the failure-classification ledger
    (``status.failures``): which attempt failed, how it was classified
    (FailureKind), and why — the record the retry budgets are computed
    from, and the postmortem trail ``kubectl get -o yaml`` shows."""

    attempt: int = 0
    kind: str = FailureKind.APPLICATION
    reason: str = ""
    time: str = ""
    # Last durable checkpoint step known when the restart was recorded —
    # the step the next attempt resumes from (None: job never reported
    # checkpoint state; the postmortem then knows the restart was cold).
    resume_step: Optional[int] = None
    # World size (whole slices) the failed attempt ran at — recorded for
    # elastic jobs so a post-resize restart is auditable: which size ran
    # and which step it resumed from live in ONE record (None: rigid
    # job, the size is always spec.numSlices).
    world_slices: Optional[int] = None
    # Steps of progress the restart discarded: last heartbeat step minus
    # the resume step (never negative). The fleet rollup prices
    # preemption cost in step-seconds from THIS, not a re-derivation —
    # the ledger is the one durable record of what each restart cost
    # (None: pre-upgrade record, or the attempt never heartbeated).
    lost_steps: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        d = {"attempt": self.attempt, "kind": self.kind,
             "reason": self.reason, "time": self.time}
        if self.resume_step is not None:
            d["resumeStep"] = self.resume_step
        if self.world_slices is not None:
            d["worldSlices"] = self.world_slices
        if self.lost_steps is not None:
            d["lostSteps"] = self.lost_steps
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FailureRecord":
        return cls(
            attempt=int(d.get("attempt", 0)),
            kind=str(d.get("kind", FailureKind.APPLICATION)),
            reason=str(d.get("reason", "")),
            time=str(d.get("time", "")),
            resume_step=(int(d["resumeStep"])
                         if d.get("resumeStep") is not None else None),
            world_slices=(int(d["worldSlices"])
                          if d.get("worldSlices") is not None else None),
            lost_steps=(int(d["lostSteps"])
                        if d.get("lostSteps") is not None else None),
        )


@dataclass
class TPUJobStatus:
    """Job status written back to the CRD (ref: types.go:117-135)."""

    phase: str = TPUJobPhase.NONE
    reason: str = ""
    state: str = State.UNKNOWN
    replica_statuses: List[TPUReplicaStatus] = field(default_factory=list)
    # TPU-native: whole-group restart attempt counter.
    attempt: int = 0
    # Observability: RFC3339 timestamp of the *first* entry into each phase
    # (trainer/training.py stamps transitions); derived durations — time to
    # scheduled/running, total runtime — come straight from this map.
    phase_timeline: Dict[str, str] = field(default_factory=dict)
    # Last training-step heartbeat posted by the payload (process 0) via the
    # status server: {step, stepTimeSeconds, tokensPerSec, loss, time, ...}.
    # ``kubectl get -o yaml`` shows a hung slice as a stale timestamp here.
    last_heartbeat: Optional[Dict[str, Any]] = None
    # Checkpoint durability state, folded in from heartbeat fields by the
    # controller: lastCheckpointStep (newest VERIFIED step — the step a
    # restart actually resumes from, distinct from whatever is merely
    # latest on disk), lifetime saveFailures/restoreFallbacks totals, and
    # the per-attempt baselines the delta accounting persists
    # (attempt/attemptSaveFailures/attemptRestoreFallbacks).
    checkpoint: Optional[Dict[str, Any]] = None
    # Warm-restart observability, folded in from the heartbeat's one-shot
    # post after the first step of each attempt: the startup-phase
    # breakdown {rendezvousSeconds, restoreSeconds, compileSeconds,
    # firstStepSeconds, cacheHit, attempt, time}. ``cacheHit`` is whether
    # the XLA compile was served from the persistent compilation cache —
    # the number that proves (or disproves) the warm-restart fast path on
    # a live job.
    startup: Optional[Dict[str, Any]] = None
    # Remote warm-start store roll-up, folded in from heartbeat fields by
    # the controller: lastUploadedStep (newest checkpoint step durable
    # REMOTELY — what a fresh-node restart can actually warm-start from,
    # distinct from checkpoint.lastCheckpointStep which may be local-only),
    # lifetime uploadFailures, and the per-attempt baseline the delta
    # accounting persists (attempt/attemptUploadFailures).
    store: Optional[Dict[str, Any]] = None
    # Restart-goodput accounting, computed by the controller from the
    # phase timeline + startup breakdown + heartbeat step cadence:
    # usefulStepSeconds (time spent in completed optimizer steps),
    # wallclockSeconds (since the job first started running), and their
    # ratio — the number that says what fleet churn (preemptions, cold
    # restarts) actually costs this job.
    goodput: Optional[Dict[str, Any]] = None
    # Data-plane phase timing, folded in from process 0's heartbeat
    # ``stepTiming`` digests: per-phase (dataWait/dispatch/compute/
    # checkpoint/host) p50/p95/max over the most recent digest window,
    # plus whole-step percentiles, attempt, and time — where step time
    # actually goes, visible from ``kubectl get -o yaml``.
    step_timing: Optional[Dict[str, Any]] = None
    # Gang straggler roll-up, computed by the controller from EVERY
    # process's cadence beats: members whose p95 step time exceeds the
    # gang median by spec.stepTrace.stragglerRatio, newest evaluation
    # (empty/absent = gang healthy). Each entry: {processId, p95Seconds,
    # gangMedianSeconds, ratio, step, time}.
    stragglers: List[Dict[str, Any]] = field(default_factory=list)
    # Self-tuning data plane, folded in from process 0's heartbeat
    # ``dataPlane`` knob reports: live prefetch depth, host-path mode,
    # effective checkpoint cadence, lifetime per-knob adjustment totals
    # (delta-accumulated like the checkpoint counters, with per-attempt
    # baselines persisted IN status so operator restarts never
    # double-count), attempt, and time.
    data_plane: Optional[Dict[str, Any]] = None
    # Elastic-gang state, written by the controller per attempt: the
    # granted world size ({slices, workers}), the effective range, a
    # lifetime resize counter + last direction, the one-attempt shed cap
    # (capNextAttempt, consumed at the next sizing), and the bounded
    # straggler-remediation audit trail.
    elastic: Optional[Dict[str, Any]] = None
    # Serving-mode roll-up (mode: serve), aggregated by the controller
    # from every replica's serving heartbeats: {replicas (current target
    # the reconcile runs), desiredReplicas (traffic-derived), replicasReady,
    # requestsPerSecond, p50/p95LatencySeconds, loadedStep (the snapshot
    # step every READY replica serves — the hot-reload progress signal),
    # reloads (lifetime weight reloads, delta-accounted), attemptReloads
    # (per-process baselines of that accounting), attempt, time}.
    serving: Optional[Dict[str, Any]] = None
    # On-demand deep-profile state, written by the controller:
    # {id, state (Requested -> Captured), steps, time} when a
    # ``tpujobctl profile`` directive is in flight, plus
    # {capturedSteps, artifactKey, attempt} once process 0's capture
    # result folds back in. One directive at a time; a new request
    # overwrites a Captured record.
    profile: Optional[Dict[str, Any]] = None
    # Cooperative-drain state, written by the controller: {id, state
    # (Requested → Acked → Completed | Expired), reason (resize |
    # preemption | maintenance), attempt, deadline (RFC3339), time},
    # plus targetSlices for a resize drain and drainedStep once the
    # payload's planned exit is classified. One directive at a time; a
    # new request overwrites a terminal (Completed/Expired) record.
    drain: Optional[Dict[str, Any]] = None
    # Fleet-scheduling state, written by the controller: the effective
    # {queue, priority} the admission queue used and — while phase is
    # Queued — the job's ``position`` in admission order (0 = next).
    # Position updates are deliberately coarsened (material changes only)
    # so a 5k-job queue draining does not turn into a status-write storm.
    scheduling: Optional[Dict[str, Any]] = None
    # Time-aware recovery state:
    # RFC3339 stamp of the most recent phase *change* (unlike phaseTimeline,
    # which keeps only the first entry into each phase) — the stall
    # watchdog's fallback baseline for jobs that have not heartbeated since
    # the current attempt started running.
    last_transition_time: str = ""
    # While phase is Backoff: RFC3339 release time of the next gang-create.
    backoff_until: str = ""
    # Failure-classification ledger (newest last, bounded at
    # FAILURE_LEDGER_CAP) — the human-readable postmortem trail.
    failures: List[FailureRecord] = field(default_factory=list)
    # Per-kind lifetime failure counters — the retry budgets are charged
    # against THESE, not the bounded ledger (whose eviction would otherwise
    # silently re-arm an exhausted budget).
    restart_counts: Dict[str, int] = field(default_factory=dict)
    # Failures since the job last ran healthily for a sustained stretch —
    # the restart-backoff exponent (decays, unlike the lifetime counters).
    consecutive_failures: int = 0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "phase": self.phase,
            "reason": self.reason,
            "state": self.state,
            "replicaStatuses": [r.to_dict() for r in self.replica_statuses],
            "attempt": self.attempt,
        }
        if self.phase_timeline:
            d["phaseTimeline"] = dict(self.phase_timeline)
        if self.last_heartbeat:
            d["lastHeartbeat"] = dict(self.last_heartbeat)
        if self.checkpoint:
            d["checkpoint"] = dict(self.checkpoint)
        if self.startup:
            d["startup"] = dict(self.startup)
        if self.store:
            d["store"] = dict(self.store)
        if self.goodput:
            d["goodput"] = dict(self.goodput)
        if self.step_timing:
            d["stepTiming"] = dict(self.step_timing)
        if self.stragglers:
            d["stragglers"] = [dict(s) for s in self.stragglers]
        if self.data_plane:
            d["dataPlane"] = dict(self.data_plane)
        if self.elastic:
            d["elastic"] = dict(self.elastic)
        if self.serving:
            d["serving"] = dict(self.serving)
        if self.profile:
            d["profile"] = dict(self.profile)
        if self.drain:
            d["drain"] = dict(self.drain)
        if self.scheduling:
            d["scheduling"] = dict(self.scheduling)
        if self.last_transition_time:
            d["lastTransitionTime"] = self.last_transition_time
        if self.backoff_until:
            d["backoffUntil"] = self.backoff_until
        if self.failures:
            d["failures"] = [f.to_dict() for f in self.failures]
        if self.restart_counts:
            d["restartCounts"] = dict(self.restart_counts)
        if self.consecutive_failures:
            d["consecutiveFailures"] = self.consecutive_failures
        return d

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "TPUJobStatus":
        d = d or {}
        return cls(
            phase=str(d.get("phase", TPUJobPhase.NONE)),
            reason=str(d.get("reason", "")),
            state=str(d.get("state", State.UNKNOWN)),
            replica_statuses=[
                TPUReplicaStatus.from_dict(r) for r in d.get("replicaStatuses", [])
            ],
            attempt=int(d.get("attempt", 0)),
            phase_timeline={
                str(k): str(v)
                for k, v in (d.get("phaseTimeline") or {}).items()
            },
            last_heartbeat=(dict(d["lastHeartbeat"])
                            if d.get("lastHeartbeat") else None),
            checkpoint=(dict(d["checkpoint"])
                        if d.get("checkpoint") else None),
            startup=(dict(d["startup"]) if d.get("startup") else None),
            store=(dict(d["store"]) if d.get("store") else None),
            goodput=(dict(d["goodput"]) if d.get("goodput") else None),
            step_timing=(dict(d["stepTiming"])
                         if d.get("stepTiming") else None),
            stragglers=[dict(s) for s in d.get("stragglers", [])],
            data_plane=(dict(d["dataPlane"])
                        if d.get("dataPlane") else None),
            elastic=(dict(d["elastic"]) if d.get("elastic") else None),
            serving=(dict(d["serving"]) if d.get("serving") else None),
            profile=(dict(d["profile"]) if d.get("profile") else None),
            drain=(dict(d["drain"]) if d.get("drain") else None),
            scheduling=(dict(d["scheduling"])
                        if d.get("scheduling") else None),
            last_transition_time=str(d.get("lastTransitionTime", "")),
            backoff_until=str(d.get("backoffUntil", "")),
            failures=[FailureRecord.from_dict(f)
                      for f in d.get("failures", [])],
            restart_counts={str(k): int(v) for k, v in
                            (d.get("restartCounts") or {}).items()},
            consecutive_failures=int(d.get("consecutiveFailures", 0)),
        )


# --- The CRD object ---------------------------------------------------------

@dataclass
class TPUJob:
    """A TPUJob object (ref: types.go:41-52)."""

    metadata: Dict[str, Any] = field(default_factory=dict)
    spec: TPUJobSpec = field(default_factory=TPUJobSpec)
    status: TPUJobStatus = field(default_factory=TPUJobStatus)

    @property
    def name(self) -> str:
        return self.metadata.get("name", "")

    @property
    def namespace(self) -> str:
        return self.metadata.get("namespace", "default")

    @property
    def uid(self) -> str:
        return self.metadata.get("uid", "")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": CRD_API_VERSION,
            "kind": CRD_KIND,
            "metadata": copy.deepcopy(self.metadata),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUJob":
        return cls(
            metadata=copy.deepcopy(d.get("metadata") or {}),
            spec=TPUJobSpec.from_dict(d.get("spec") or {}),
            status=TPUJobStatus.from_dict(d.get("status")),
        )

    def deepcopy(self) -> "TPUJob":
        """Value-semantics copy (ref: zz_generated.deepcopy.go)."""
        return TPUJob.from_dict(self.to_dict())


# --- Controller config (ref: types.go:170-196) ------------------------------

@dataclass
class TPUAcceleratorVolume:
    """A hostPath mount injected for a matched accelerator
    (ref: types.go:188-196 AcceleratorVolume)."""

    name: str
    host_path: str
    mount_path: str

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "hostPath": self.host_path, "mountPath": self.mount_path}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUAcceleratorVolume":
        return cls(
            name=str(d.get("name", "")),
            host_path=str(d.get("hostPath", "")),
            mount_path=str(d.get("mountPath", "")),
        )


@dataclass
class TPUAcceleratorConfig:
    """Per-accelerator injection recipe (ref: types.go:182-186).

    For TPU resource names (``cloud-tpus.google.com/v4`` etc.) the useful
    payload is **env injection** (topology, runtime addresses) rather than the
    CUDA hostPath volumes the reference mounts for
    ``alpha.kubernetes.io/nvidia-gpu``; both are supported.
    """

    volumes: List[TPUAcceleratorVolume] = field(default_factory=list)
    env_vars: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "volumes": [v.to_dict() for v in self.volumes],
            "envVars": dict(self.env_vars),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUAcceleratorConfig":
        env = d.get("envVars") or {}
        # Accept both map form {NAME: value} and list form [{name,value}]
        # (the reference uses a list of EnvironmentVariableConfig,
        # types.go:182-186; the map form is friendlier YAML).
        if isinstance(env, list):
            env = {e.get("name", ""): str(e.get("value", "")) for e in env}
        return cls(
            volumes=[TPUAcceleratorVolume.from_dict(v) for v in d.get("volumes", [])],
            env_vars={str(k): str(v) for k, v in env.items()},
        )


def _debounce_seconds(value: Any) -> float:
    """Validate ``nodeDebounceSeconds``: a negative window is a config
    error (there is no 'apply shrinks from the past'), not a silent 0."""
    seconds = float(value)
    if seconds < 0:
        raise ValueError(
            f"nodeDebounceSeconds must be >= 0, got {value!r}")
    return seconds


@dataclass
class ControllerConfig:
    """Admin-provided operator config (ref: types.go:170-178).

    ``accelerators`` maps a Kubernetes resource name to its injection recipe.
    ``status_url`` is the operator's advertised status-server base URL
    (``--advertise-status-url`` / config ``statusUrl``); when set, worker
    pods get ``TPUJOB_STATUS_URL`` so payloads can post step heartbeats.
    ``create_parallelism`` (``--create-parallelism`` / config
    ``createParallelism``) bounds the concurrent child-create RPCs per gang
    sync; 1 degrades to the sequential path.
    ``slice_inventory`` (``sliceInventory`` / ``--slice-inventory``) is the
    static fleet-scheduler capacity model: ``"<resource>:<topology>" →
    whole slices`` (e.g. ``"cloud-tpus.google.com/v4:2x2x2": 8``). Empty =
    no admission control (every job admits immediately, the pre-fleet
    behavior); a key absent from a non-empty map is treated as unmodeled
    (unlimited) so a typo queues nothing forever.
    The reference also carried an unused ``GrpcServerFilePath`` field
    (types.go:176-177) — deliberately dropped here (SURVEY.md "quirks to
    fix, not copy").
    """

    accelerators: Dict[str, TPUAcceleratorConfig] = field(default_factory=dict)
    status_url: str = ""
    create_parallelism: int = 16
    slice_inventory: Dict[str, int] = field(default_factory=dict)
    # Live slice-inventory discovery (``discoverSliceInventory`` /
    # ``--discover-slice-inventory``): the controller watches node objects
    # and rebuilds the fleet scheduler's capacity model on every node
    # add/remove/relabel — so capacity changes admit queued gangs without
    # an operator restart. When set alongside a static ``sliceInventory``,
    # the discovered model wins as soon as the node cache syncs.
    discover_slice_inventory: bool = False
    # Debounce window for discovered-capacity SHRINKS (``nodeDebounceSeconds``
    # / ``--node-debounce-seconds``): a NotReady→Ready flap inside the
    # window must not churn the fleet scheduler through a shrink/regrow
    # rebalance cycle. Growth always applies immediately — a new node
    # admitting a queued gang must never wait out a flap timer. 0 disables
    # (every node event applies verbatim, the pre-debounce behavior).
    node_debounce_seconds: float = 5.0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "accelerators": {k: v.to_dict() for k, v in self.accelerators.items()}
        }
        if self.status_url:
            d["statusUrl"] = self.status_url
        if self.create_parallelism != 16:
            d["createParallelism"] = self.create_parallelism
        if self.slice_inventory:
            d["sliceInventory"] = dict(self.slice_inventory)
        if self.discover_slice_inventory:
            d["discoverSliceInventory"] = True
        if self.node_debounce_seconds != 5.0:
            d["nodeDebounceSeconds"] = self.node_debounce_seconds
        return d

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ControllerConfig":
        d = d or {}
        inventory: Dict[str, int] = {}
        for k, v in (d.get("sliceInventory") or {}).items():
            if int(v) < 1:
                # Zero/negative capacity would silently queue every job of
                # this shape forever — fail the admin config loudly instead.
                raise ValueError(
                    f"sliceInventory[{k!r}] must be >= 1, got {v!r}")
            if ":" not in str(k):
                # Demand keys are '<resource>:<topology>'; a colon-less
                # key matches nothing and silently disables admission
                # control for the shape it was meant to model.
                raise ValueError(
                    f"sliceInventory key {k!r} must be "
                    f"'<resource>:<topology>' ('{k}:' for topology-less)")
            inventory[str(k)] = int(v)
        return cls(
            accelerators={
                str(k): TPUAcceleratorConfig.from_dict(v or {})
                for k, v in (d.get("accelerators") or {}).items()
            },
            status_url=str(d.get("statusUrl", "")),
            create_parallelism=int(d.get("createParallelism", 16) or 16),
            slice_inventory=inventory,
            discover_slice_inventory=bool(
                d.get("discoverSliceInventory", False)),
            node_debounce_seconds=_debounce_seconds(
                d.get("nodeDebounceSeconds", 5.0)),
        )
