"""Scheme registration: GroupVersionKind ↔ Python type mapping.

Reference parity: pkg/apis/mxnet/v1alpha1/register.go:27-68 (SchemeBuilder,
GroupVersion, addKnownTypes) — the Go scheme machinery exists to let generic
client code decode wire objects into typed structs; this module is the
Python equivalent used by the clientset and the fake apiserver.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

from tpu_operator.apis.tpujob.v1alpha1 import types as v1alpha1

# (apiVersion, kind) -> decoder
_SCHEME: Dict[Tuple[str, str], Callable[[Dict[str, Any]], Any]] = {}


def add_known_type(api_version: str, kind: str, decoder: Callable[[Dict[str, Any]], Any]) -> None:
    _SCHEME[(api_version, kind)] = decoder


def decode(obj: Dict[str, Any]) -> Any:
    """Decode a wire dict into its registered type; returns the dict
    unchanged for unregistered kinds (raw passthrough, like runtime.Unknown)."""
    key = (obj.get("apiVersion", ""), obj.get("kind", ""))
    dec = _SCHEME.get(key)
    return dec(obj) if dec else obj


def group_version() -> str:
    return v1alpha1.CRD_API_VERSION


def crd_name() -> str:
    """Full CRD name ``tpujobs.tpuoperator.dev``
    (ref: helper/helpers.go:120-123 CRDName)."""
    return f"{v1alpha1.CRD_KIND_PLURAL}.{v1alpha1.CRD_GROUP}"


# Register known types (ref: register.go:55-66 addKnownTypes registers
# MXJob and MXJobList).
add_known_type(v1alpha1.CRD_API_VERSION, v1alpha1.CRD_KIND, v1alpha1.TPUJob.from_dict)
