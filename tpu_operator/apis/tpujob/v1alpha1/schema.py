"""Structural openAPIV3Schema for the TPUJob CRD, generated from types.py.

The reference's CRD carried no schema at all (examples/crd.yml:1-11 — v1beta1
CRDs predate structural schemas), and round 1 shipped
``x-kubernetes-preserve-unknown-fields: true``, which let a typo'd field
(``maxRestart:``) through to be silently defaulted by the operator. This
module is the single source of truth for the structural schema:

- ``deploy/chart/.../crd.yaml`` and ``examples/crd.yml`` embed it via
  ``hack/gen_crd.py`` (``hack/verify.sh`` fails on drift);
- the in-process test apiserver (tpu_operator/testing/apiserver.py)
  validates every TPUJob create/update against it in *strict* mode —
  unknown fields are rejected with 422, which is kubectl's
  ``--validate=strict`` behavior and exactly what catches the typo case
  (a real apiserver would prune instead, which still prevents the silent
  defaulting but hides the mistake);
- ``validate_strict`` below is that validator: types, enums, integer
  bounds, and unknown-field rejection, with the PodTemplateSpec subtree
  (``spec.replicaSpecs[].template``) deliberately permissive — we keep the
  reference's "don't hide Kubernetes" passthrough (tf_job_design_doc.md:73),
  and its schema belongs to the pod API, not this CRD.

Enums and bounds mirror types.py/validation.py: replica types
(TPUReplicaType.ALL), restart policies (RestartPolicy.ALL), phases/states
for the status subresource, port 1-65535, non-negative counters.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from tpu_operator.apis.tpujob.v1alpha1 import types
# Stage names of the warm-restart startup breakdown. payload/startup.py is
# the canonical home (the payload emits them); it is stdlib-only, so the
# schema importing it drags nothing heavy into the control plane.
from tpu_operator.payload.startup import STAGES as STARTUP_STAGES
# Phase field names of the data-plane flight recorder (payload/steptrace.py,
# stdlib-only for the same reason): the keys of stepTiming.phases.
from tpu_operator.payload.steptrace import (
    DIGEST_KEYS as STEP_DIGEST_KEYS,
    PHASE_FIELDS as STEP_PHASE_FIELDS,
)
# Per-knob adjustment-counter keys of the self-tuning data plane
# (payload/autotune.py, stdlib-only as well): the keys of
# dataPlane.adjustments.
from tpu_operator.payload.autotune import (
    ADJUSTMENT_KEYS,
    MIN_WINDOW_STEPS,
)


def _str(**kw) -> Dict[str, Any]:
    return {"type": "string", **kw}


def _int(minimum=None, maximum=None) -> Dict[str, Any]:
    out: Dict[str, Any] = {"type": "integer"}
    if minimum is not None:
        out["minimum"] = minimum
    if maximum is not None:
        out["maximum"] = maximum
    return out


def _num(minimum=None) -> Dict[str, Any]:
    out: Dict[str, Any] = {"type": "number"}
    if minimum is not None:
        out["minimum"] = minimum
    return out


def _obj(properties: Dict[str, Any], required: List[str] = ()) -> Dict[str, Any]:
    out: Dict[str, Any] = {"type": "object", "properties": properties}
    if required:
        out["required"] = list(required)
    return out


def _arr(items: Dict[str, Any]) -> Dict[str, Any]:
    return {"type": "array", "items": items}


PRESERVE = {"type": "object", "x-kubernetes-preserve-unknown-fields": True}


def replica_spec_schema() -> Dict[str, Any]:
    return _obj({
        "replicas": _int(minimum=1),
        # PodTemplateSpec passthrough: schema'd by the pod API, not us.
        "template": dict(PRESERVE),
        # nullable: an explicit ``tpuPort: null`` is meaningful to
        # validation (it must flag it, not default it).
        "tpuPort": {"type": "integer", "minimum": 1, "maximum": 65535,
                    "nullable": True},
        "tpuReplicaType": _str(enum=list(types.TPUReplicaType.ALL)),
    })


def spec_schema() -> Dict[str, Any]:
    return _obj({
        "replicaSpecs": _arr(replica_spec_schema()),
        "terminationPolicy": _obj({
            "chief": _obj({
                "replicaName": _str(enum=list(types.TPUReplicaType.ALL)),
                "replicaIndex": _int(minimum=0),
            }),
        }),
        "runtimeId": _str(),
        "schedulerName": _str(),
        "restartPolicy": _str(enum=list(types.RestartPolicy.ALL)),
        "maxRestarts": _int(minimum=0),
        "tpuTopology": _str(pattern=r"^\d+x\d+(x\d+)?$"),
        "numSlices": _int(minimum=1),
        "checkpointDir": _str(),
        "profileDir": _str(),
        "suspend": {"type": "boolean"},
        # Time-aware recovery (batch/v1 Job analogues).
        "activeDeadlineSeconds": _int(minimum=1),
        "stallTimeoutSeconds": _int(minimum=1),
        "ttlSecondsAfterFinished": _int(minimum=0),
        "restartBackoff": _obj({
            "baseSeconds": _int(minimum=0),
            "maxSeconds": _int(minimum=0),
        }),
        # Warm-restart fast path: persistent XLA compilation cache.
        "compilationCache": _obj({
            "enabled": {"type": "boolean"},
            "path": _str(),
            "medium": _str(enum=list(types.CacheMedium.ALL)),
        }),
        # Fleet scheduling: admission priority + fair-share queue.
        "scheduling": _obj({
            "priority": _int(minimum=-types.MAX_SCHEDULING_PRIORITY,
                             maximum=types.MAX_SCHEDULING_PRIORITY),
            "queue": _str(),
        }),
        # Remote warm-start store: write-behind checkpoint/cache uploads
        # + rendezvous-overlapped prefetch for fresh-node warm restarts.
        # backend is a PATTERN, not an enum: beyond the in-repo localfs/
        # fake, any slug may name a deployment-registered backend
        # (store/blob.register_backend); validation.py enforces URI-scheme
        # consistency.
        "store": _obj({
            "backend": _str(pattern=types.StoreBackend.NAME_PATTERN),
            "uri": _str(),
            "uploadParallelism": _int(minimum=1),
            "prefetch": {"type": "boolean"},
            # Retention GC: newest-N verified snapshots kept remotely
            # (0 = keep everything), enforced by the write-behind worker.
            "keepSnapshots": _int(minimum=0),
        }),
        # Job mode: absent/"train" = finite training job; "serve" =
        # long-lived inference gang (readiness-gated Services, hot
        # weight reload, traffic-driven replica scaling).
        "mode": _str(enum=list(types.JobMode.ALL)),
        # Serving-mode scaling + tail-latency policy (mode: serve).
        "serving": _obj({
            "minReplicas": _int(minimum=1),
            "maxReplicas": _int(minimum=0),
            "targetRequestsPerSecondPerReplica": _num(minimum=0),
            "reloadPollSeconds": _int(minimum=1),
            "stragglerPolicy": _str(enum=[types.StragglerPolicy.NONE,
                                          types.StragglerPolicy.REPLACE]),
            "stragglerPatienceSeconds": _int(minimum=1),
        }),
        # Data-plane flight recorder: per-step phase timing ring buffer
        # (payload side) + the controller's straggler-flagging threshold.
        "stepTrace": _obj({
            "enabled": {"type": "boolean"},
            "bufferSteps": _int(minimum=8),
            "stragglerRatio": _num(minimum=1),
        }),
        # Self-tuning data plane: prefetch depth (0 = auto) + the
        # closed-loop autotuner's bounds and evaluation window.
        "dataPlane": _obj({
            "prefetchDepth": _int(minimum=0),
            "autotune": _obj({
                "enabled": {"type": "boolean"},
                "minDepth": _int(minimum=0),
                "maxDepth": _int(minimum=1),
                "windowSteps": _int(minimum=MIN_WINDOW_STEPS),
            }),
        }),
        # Elastic gangs: each attempt's world size is picked from the
        # live slice inventory within [minSlices, maxSlices] (maxSlices
        # 0 = defaulted to numSlices), and persistently flagged
        # stragglers are replaced or shed per stragglerPolicy.
        "elastic": _obj({
            "minSlices": _int(minimum=1),
            "maxSlices": _int(minimum=0),
            "stragglerPolicy": _str(enum=list(types.StragglerPolicy.ALL)),
            "stragglerPatienceSeconds": _int(minimum=1),
        }),
        # Cooperative drain protocol: per-directive deadline before the
        # hard-teardown fallback, and the in-attempt grow-trigger
        # debounce window.
        "drain": _obj({
            "deadlineSeconds": _int(minimum=1),
            "resizeDebounceSeconds": _int(minimum=0),
        }),
    }, required=["replicaSpecs"])


def startup_breakdown_schema() -> Dict[str, Any]:
    """The startup-phase breakdown object: shared by
    ``status.lastHeartbeat.startup`` (as posted) and ``status.startup``
    (as folded in by the controller, which adds attempt/time)."""
    return _obj({
        "rendezvousSeconds": _num(minimum=0),
        # Remote warm-start store: time the prefetch (compilation cache +
        # latest checkpoint download, overlapped with rendezvous) kept on
        # the critical path, and whether it delivered anything.
        "prefetchSeconds": _num(minimum=0),
        "prefetchHit": {"type": "boolean"},
        "restoreSeconds": _num(minimum=0),
        "compileSeconds": _num(minimum=0),
        "firstStepSeconds": _num(minimum=0),
        "cacheHit": {"type": "boolean"},
        "attempt": _int(minimum=0),
        "time": _str(),
    })


def steptiming_schema() -> Dict[str, Any]:
    """The data-plane phase-timing digest: shared by
    ``status.lastHeartbeat.stepTiming`` (as posted, one window's
    percentiles) and ``status.stepTiming`` (as folded in by the
    controller, which adds attempt/processId/time)."""
    return _obj({
        "steps": _int(minimum=0),
        "stepP50Seconds": _num(minimum=0),
        "stepP95Seconds": _num(minimum=0),
        "stepMaxSeconds": _num(minimum=0),
        # p95 of per-step LOCAL time (step minus the compute wait): the
        # straggler detector's per-process signal — whole-step cadence is
        # gang-synchronized by the collectives and cannot single anyone
        # out.
        "stepLocalP95Seconds": _num(minimum=0),
        "phases": _obj({
            field: _obj({key: _num(minimum=0) for key in STEP_DIGEST_KEYS})
            for field in STEP_PHASE_FIELDS.values()
        }),
        "attempt": _int(minimum=0),
        "processId": _int(minimum=0),
        "time": _str(),
    })


def dataplane_knobs_schema(status: bool = False) -> Dict[str, Any]:
    """The self-tuning data plane's knob report: shared by
    ``status.lastHeartbeat.dataPlane`` (as posted — live values +
    per-attempt adjustment counters) and ``status.dataPlane`` (as folded
    in by the controller, which adds lifetime totals, the per-attempt
    delta baselines, attempt, and time)."""
    counters = _obj({key: _int(minimum=0) for key in ADJUSTMENT_KEYS})
    out = {
        # Live device-prefetch depth (in-flight batch window).
        "prefetchDepth": _int(minimum=0),
        # Heartbeat/log work on the async host worker vs the step thread.
        "hostAsync": {"type": "boolean"},
        # Effective checkpoint save interval after any autotune stretch.
        "checkpointIntervalSteps": _int(minimum=1),
        # Telemetry work shed by the async host worker (lossy by
        # contract, but never invisibly).
        "hostDropped": _int(minimum=0),
        "adjustments": counters,
    }
    if status:
        out.update({
            # Per-attempt baselines of the delta accounting (the payload
            # counters reset on whole-group restart; lifetime totals in
            # ``adjustments`` accumulate deltas against these).
            "attemptAdjustments": _obj(
                {key: _int(minimum=0) for key in ADJUSTMENT_KEYS}),
            "attempt": _int(minimum=0),
            "time": _str(),
        })
    return _obj(out)


def serving_beat_schema() -> Dict[str, Any]:
    """One replica's serving heartbeat body (``lastHeartbeat.serving``,
    as posted): readiness, its slice of the traffic, its latency
    percentiles over the reporting window, the snapshot step it serves,
    and its per-attempt weight-reload counter (the controller's delta
    accounting aggregates these into ``status.serving``)."""
    return _obj({
        "ready": {"type": "boolean"},
        "requestsPerSecond": _num(minimum=0),
        # Decode throughput of the paged KV-cache engine (tokens emitted
        # over the reporting window) — the bench's A/B currency.
        "tokensPerSecond": _num(minimum=0),
        # Ingress backpressure signals: requests waiting for a slot, and
        # the fraction of the KV page pool held by live requests.
        "queueDepth": _int(minimum=0),
        "kvCacheUtilization": _num(minimum=0),
        "p50LatencySeconds": _num(minimum=0),
        "p95LatencySeconds": _num(minimum=0),
        "loadedStep": _int(minimum=0),
        "reloads": _int(minimum=0),
    })


def serving_status_schema() -> Dict[str, Any]:
    """The controller's serving roll-up (``status.serving``): the current
    and traffic-desired replica counts, readiness, aggregate traffic and
    tail latency, the gang-wide loaded snapshot step, and the lifetime
    weight-reload total with its per-process delta baselines."""
    return _obj({
        "replicas": _int(minimum=0),
        "desiredReplicas": _int(minimum=0),
        "replicasReady": _int(minimum=0),
        "requestsPerSecond": _num(minimum=0),
        # Fleet decode throughput (sum over ready replicas), total queued
        # backlog, and the worst replica's KV page-pool utilization.
        "tokensPerSecond": _num(minimum=0),
        "queueDepth": _int(minimum=0),
        "kvCacheUtilization": _num(minimum=0),
        "p50LatencySeconds": _num(minimum=0),
        "p95LatencySeconds": _num(minimum=0),
        "loadedStep": _int(minimum=0),
        "reloads": _int(minimum=0),
        # Per-process reload-counter baselines of the delta accounting
        # (payload counters reset on replica restart; lifetime ``reloads``
        # accumulates deltas against these, persisted IN status so an
        # operator restart never double-counts).
        "attemptReloads": {
            "type": "object",
            "additionalProperties": _int(minimum=0),
        },
        "attempt": _int(minimum=0),
        "time": _str(),
    })


def status_schema() -> Dict[str, Any]:
    phases = [types.TPUJobPhase.NONE, types.TPUJobPhase.CREATING,
              types.TPUJobPhase.RUNNING, types.TPUJobPhase.CLEANUP,
              types.TPUJobPhase.FAILED, types.TPUJobPhase.DONE,
              types.TPUJobPhase.SUSPENDED, types.TPUJobPhase.BACKOFF,
              types.TPUJobPhase.QUEUED]
    states = [types.State.UNKNOWN, types.State.RUNNING,
              types.State.SUCCEEDED, types.State.FAILED]
    replica_states = [types.ReplicaState.UNKNOWN, types.ReplicaState.STARTING,
                      types.ReplicaState.RUNNING, types.ReplicaState.SUCCEEDED,
                      types.ReplicaState.FAILED]
    return _obj({
        "phase": _str(enum=phases),
        "reason": _str(),
        "state": _str(enum=states),
        "attempt": _int(minimum=0),
        "replicaStatuses": _arr(_obj({
            "tpuReplicaType": _str(enum=list(types.TPUReplicaType.ALL)),
            "state": _str(enum=replica_states),
            "replicasStates": {
                "type": "object",
                "additionalProperties": _int(minimum=0),
            },
        })),
        # First-entry timestamp per phase (RFC3339); keys are phase names,
        # which excludes the empty NONE phase by construction.
        "phaseTimeline": {
            "type": "object",
            "additionalProperties": _str(),
        },
        # Last payload heartbeat (statusserver POST /api/heartbeat).
        "lastHeartbeat": _obj({
            "step": _int(minimum=0),
            "attempt": _int(minimum=0),
            "processId": _int(minimum=0),
            "stepTimeSeconds": _num(minimum=0),
            "tokensPerSec": _num(minimum=0),
            "loss": _num(),
            "time": _str(),
            # Checkpoint durability fields (payload/checkpoint.py stats).
            "lastCheckpointStep": _int(minimum=0),
            "checkpointSaveFailures": _int(minimum=0),
            "checkpointRestoreFallbacks": _int(minimum=0),
            # Remote warm-start store fields (write-behind uploader).
            "storeLastUploadedStep": _int(minimum=0),
            "storeUploadFailures": _int(minimum=0),
            # Warm-restart startup telemetry: pre-first-step liveness beats
            # carry the in-flight stage; the post-first-step beat carries
            # the full breakdown (folded into status.startup).
            "startupStage": _str(enum=list(STARTUP_STAGES)),
            "startup": startup_breakdown_schema(),
            # Data-plane phase digest (flight recorder window summary).
            "stepTiming": steptiming_schema(),
            # Self-tuning data plane knob report (live values).
            "dataPlane": dataplane_knobs_schema(),
            # Serving-mode beat (mode: serve replicas post these).
            "serving": serving_beat_schema(),
            # On-demand deep-profile result (process 0, one-shot until
            # the controller ACKs it by folding status.profile).
            "profile": _obj({
                "id": _str(),
                "capturedSteps": _int(minimum=0),
                "artifactKey": _str(),
            }),
            # Cooperative-drain ACK (process 0, one-shot until the
            # controller folds status.drain to Acked).
            "drainAck": _obj({
                "id": _str(),
                "step": _int(minimum=0),
            }),
        }),
        # Checkpoint durability roll-up: the last VERIFIED (durable) step,
        # lifetime save-failure / restore-fallback totals, and the
        # per-attempt baselines the controller's delta accounting persists.
        "checkpoint": _obj({
            "lastCheckpointStep": _int(minimum=0),
            "saveFailures": _int(minimum=0),
            "restoreFallbacks": _int(minimum=0),
            "attempt": _int(minimum=0),
            "attemptSaveFailures": _int(minimum=0),
            "attemptRestoreFallbacks": _int(minimum=0),
            "time": _str(),
        }),
        # Warm-restart observability: the per-attempt startup-phase
        # breakdown (rendezvous/restore/compile/first-step seconds and
        # whether the XLA compile hit the persistent cache).
        "startup": startup_breakdown_schema(),
        # Remote warm-start store roll-up: the newest step durable
        # REMOTELY (what a fresh node warm-starts from), lifetime upload
        # failures, and the per-attempt delta-accounting baselines.
        "store": _obj({
            "lastUploadedStep": _int(minimum=0),
            "uploadFailures": _int(minimum=0),
            "attempt": _int(minimum=0),
            "attemptUploadFailures": _int(minimum=0),
            "time": _str(),
        }),
        # Restart-goodput accounting: useful-step-seconds over attempt
        # wallclock — what fleet churn actually costs this job.
        "goodput": _obj({
            "usefulStepSeconds": _num(minimum=0),
            "wallclockSeconds": _num(minimum=0),
            "ratio": _num(minimum=0),
            "attempt": _int(minimum=0),
            "lastStep": _int(minimum=0),
            "time": _str(),
        }),
        # Data-plane phase timing: where step time goes (per-phase
        # p50/p95/max over the newest digest window from process 0).
        "stepTiming": steptiming_schema(),
        # Self-tuning data plane roll-up: live knob values + lifetime
        # adjustment totals with the per-attempt delta baselines.
        "dataPlane": dataplane_knobs_schema(status=True),
        # Gang straggler roll-up: members whose p95 step time exceeds the
        # gang median by spec.stepTrace.stragglerRatio (absent = healthy).
        "stragglers": _arr(_obj({
            "processId": _int(minimum=0),
            "p95Seconds": _num(minimum=0),
            "gangMedianSeconds": _num(minimum=0),
            "ratio": _num(minimum=0),
            "step": _int(minimum=0),
            "time": _str(),
        })),
        # Elastic-gang state: the attempt's granted world size, the
        # effective range, resize accounting, the one-attempt shed cap,
        # and the bounded straggler-remediation audit trail.
        "elastic": _obj({
            "slices": _int(minimum=1),
            "workers": _int(minimum=1),
            "minSlices": _int(minimum=1),
            "maxSlices": _int(minimum=1),
            "attempt": _int(minimum=0),
            "resizes": _int(minimum=0),
            "lastResizeDirection": _str(enum=["up", "down"]),
            "capNextAttempt": _int(minimum=1),
            "time": _str(),
            "remediations": _arr(_obj({
                "attempt": _int(minimum=0),
                "processId": _int(minimum=0),
                "policy": _str(enum=[types.StragglerPolicy.REPLACE,
                                     types.StragglerPolicy.SHED]),
                "node": _str(),
                "time": _str(),
            })),
        }),
        # Serving-mode roll-up: readiness, aggregate traffic + tail
        # latency, the gang's loaded snapshot step, reload accounting.
        "serving": serving_status_schema(),
        # On-demand deep-profile directive lifecycle: Requested when the
        # ``tpujobctl profile`` annotation is admitted, Captured when
        # process 0's capture result folds back in (artifactKey names
        # the raw-laps JSON under the store's ``artifacts/`` prefix).
        "profile": _obj({
            "id": _str(),
            "state": _str(enum=["Requested", "Captured"]),
            "steps": _int(minimum=1),
            "capturedSteps": _int(minimum=0),
            "artifactKey": _str(),
            "attempt": _int(minimum=0),
            "time": _str(),
        }),
        # Cooperative-drain directive lifecycle: Requested when the
        # controller stamps a drain (resize / preemption / maintenance),
        # Acked when process 0's drainAck folds back in, Completed when
        # the payload's planned exit is classified, Expired when the
        # deadline fell back to hard teardown.
        "drain": _obj({
            "id": _str(),
            "state": _str(enum=list(types.DrainState.ALL)),
            "reason": _str(enum=list(types.DrainReason.ALL)),
            "attempt": _int(minimum=0),
            "deadline": _str(),
            "targetSlices": _int(minimum=1),
            "drainedStep": _int(minimum=0),
            "time": _str(),
        }),
        # Fleet-scheduling state: effective queue/priority, and — while
        # phase is Queued — the admission-order position (0 = next).
        "scheduling": _obj({
            "queue": _str(),
            "priority": _int(minimum=-types.MAX_SCHEDULING_PRIORITY,
                             maximum=types.MAX_SCHEDULING_PRIORITY),
            "position": _int(minimum=0),
        }),
        # Most recent phase *change* (stall-watchdog baseline; RFC3339).
        "lastTransitionTime": _str(),
        # Gang-create release time while phase is Backoff (RFC3339).
        "backoffUntil": _str(),
        # Failure-classification ledger (bounded postmortem trail).
        "failures": _arr(_obj({
            "attempt": _int(minimum=0),
            "kind": _str(enum=list(types.FailureKind.ALL)),
            "reason": _str(),
            "time": _str(),
            # Last durable checkpoint step known when the restart was
            # recorded — what the next attempt resumed from.
            "resumeStep": _int(minimum=0),
            # World size (slices) the failed attempt ran at (elastic
            # jobs): size and resume step are auditable together.
            "worldSlices": _int(minimum=1),
            # Steps of progress the restart discarded (lastStep minus
            # resumeStep) — the fleet rollup's preemption-cost input.
            "lostSteps": _int(minimum=0),
        })),
        # Lifetime failure counters by kind (retry budgets charge these).
        "restartCounts": {
            "type": "object",
            "additionalProperties": _int(minimum=0),
        },
        # Failures since the last sustained healthy stretch (backoff
        # exponent; decays, unlike restartCounts).
        "consecutiveFailures": _int(minimum=0),
    })


def tpujob_openapi_v3_schema() -> Dict[str, Any]:
    """The CRD's versions[].schema.openAPIV3Schema value."""
    return _obj({
        "apiVersion": _str(),
        "kind": _str(),
        # ObjectMeta belongs to the apiserver; structural schemas leave it
        # implicit (K8s rejects attempts to schema metadata beyond name/
        # generateName).
        "metadata": {"type": "object"},
        "spec": spec_schema(),
        "status": status_schema(),
    }, required=["spec"])


# --- strict validation (the test apiserver's admission path) -----------------

class SchemaError(ValueError):
    """One strict-validation failure, with a JSON-path-ish location."""


def _fail(path: str, msg: str):
    raise SchemaError(f"{path or '.'}: {msg}")


def validate_strict(value: Any, schema: Dict[str, Any] = None,
                    path: str = "") -> None:
    """Validate ``value`` against ``schema`` (default: the full TPUJob
    schema), *rejecting* unknown fields — kubectl --validate=strict
    semantics, stricter than apiserver pruning, so tests catch typos."""
    if schema is None:
        schema = tpujob_openapi_v3_schema()

    if value is None:
        if schema.get("nullable"):
            return
        _fail(path, "null not allowed")

    t = schema.get("type")
    if t == "object":
        if schema.get("x-kubernetes-preserve-unknown-fields"):
            if not isinstance(value, dict):
                _fail(path, f"expected object, got {type(value).__name__}")
            return
        if not isinstance(value, dict):
            _fail(path, f"expected object, got {type(value).__name__}")
        props = schema.get("properties")
        addl = schema.get("additionalProperties")
        if props is not None:
            for key in value:
                if key not in props:
                    _fail(f"{path}.{key}", "unknown field")
            for key in schema.get("required", ()):
                if key not in value:
                    _fail(f"{path}.{key}", "required field missing")
            for key, sub in props.items():
                if key in value:
                    validate_strict(value[key], sub, f"{path}.{key}")
        elif isinstance(addl, dict):
            for key, v in value.items():
                validate_strict(v, addl, f"{path}.{key}")
        return
    if t == "array":
        if not isinstance(value, list):
            _fail(path, f"expected array, got {type(value).__name__}")
        for i, v in enumerate(value):
            validate_strict(v, schema["items"], f"{path}[{i}]")
        return
    if t == "string":
        if not isinstance(value, str):
            _fail(path, f"expected string, got {type(value).__name__}")
        enum = schema.get("enum")
        if enum is not None and value not in enum:
            _fail(path, f"{value!r} not one of {enum}")
        pattern = schema.get("pattern")
        if pattern is not None:
            import re

            if not re.match(pattern, value):
                _fail(path, f"{value!r} does not match {pattern!r}")
        return
    if t == "boolean":
        if not isinstance(value, bool):
            _fail(path, f"expected boolean, got {type(value).__name__}")
        return
    if t == "integer":
        if isinstance(value, bool) or not isinstance(value, int):
            _fail(path, f"expected integer, got {type(value).__name__}")
        lo, hi = schema.get("minimum"), schema.get("maximum")
        if lo is not None and value < lo:
            _fail(path, f"{value} < minimum {lo}")
        if hi is not None and value > hi:
            _fail(path, f"{value} > maximum {hi}")
        return
    if t == "number":
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            _fail(path, f"expected number, got {type(value).__name__}")
        lo = schema.get("minimum")
        if lo is not None and value < lo:
            _fail(path, f"{value} < minimum {lo}")
        return
    _fail(path, f"unhandled schema type {t!r}")


def validate_tpujob_strict(body: Dict[str, Any]) -> Tuple[bool, str]:
    """(ok, message) for a TPUJob create/update body."""
    try:
        validate_strict(body)
        return True, ""
    except SchemaError as e:
        return False, str(e)
