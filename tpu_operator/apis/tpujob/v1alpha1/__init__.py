from tpu_operator.apis.tpujob.v1alpha1.types import *  # noqa: F401,F403
