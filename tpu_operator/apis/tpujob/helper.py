"""Helpers shared by the trainer and controller.

Reference parity: pkg/apis/mxnet/helper/helpers.go:
- ``as_owner`` ← AsOwner (helpers.go:40-52): OwnerReference stamped on every
  child pod/service so Kubernetes garbage collection cascades deletes.
- ``configure_accelerators`` ← ConfigureAcceleratorsForTFJobSpec
  (helpers.go:55-110): match container resource requests/limits against the
  admin accelerator map; inject volumes and env.
- ``crd_name`` lives in register.py.

TPU-native additions: ``tpu_chips_requested`` (counts
``cloud-tpus.google.com/*`` requests) and topology env derivation used by the
replica env injection.
"""

from __future__ import annotations

from typing import Any, Dict

from tpu_operator.apis.tpujob.v1alpha1.types import (
    ControllerConfig,
    TPU_RESOURCE_PREFIX,
    TPUJobSpec,
)


def as_owner(job_metadata: Dict[str, Any]) -> Dict[str, Any]:
    """Build the controller OwnerReference for a TPUJob's children
    (ref: helpers.go:40-52; BlockOwnerDeletion=true as in the reference)."""
    from tpu_operator.apis.tpujob.v1alpha1.types import CRD_API_VERSION, CRD_KIND

    return {
        "apiVersion": CRD_API_VERSION,
        "kind": CRD_KIND,
        "name": job_metadata.get("name", ""),
        "uid": job_metadata.get("uid", ""),
        "controller": True,
        "blockOwnerDeletion": True,
    }


def _container_accelerator_names(container: Dict[str, Any], config: ControllerConfig):
    """Resource names in this container's requests/limits that appear in the
    admin accelerator map (ref: helpers.go:62-83 scans both maps)."""
    resources = container.get("resources") or {}
    names = []
    for section in ("requests", "limits"):
        for res_name in (resources.get(section) or {}):
            if res_name in config.accelerators and res_name not in names:
                names.append(res_name)
    return names


def configure_accelerators(spec: TPUJobSpec, config: ControllerConfig) -> None:
    """Inject admin-configured volumes/env for matched accelerator resources
    (ref: helpers.go:55-110).

    The reference appends hostPath volumes + mounts + env for GPU resources;
    for TPU resource names the recipe is usually env-only (topology vars),
    but both paths are supported uniformly.
    """
    if not config.accelerators:
        return
    for rs in spec.replica_specs:
        template = rs.template
        if not template:
            continue
        pod_spec = template.setdefault("spec", {})
        for container in pod_spec.get("containers") or []:
            for res_name in _container_accelerator_names(container, config):
                acc = config.accelerators[res_name]
                # Volumes (ref: helpers.go:84-100)
                for vol in acc.volumes:
                    pod_spec.setdefault("volumes", []).append(
                        {"name": vol.name, "hostPath": {"path": vol.host_path}}
                    )
                    container.setdefault("volumeMounts", []).append(
                        {"name": vol.name, "mountPath": vol.mount_path}
                    )
                # Env (ref: helpers.go:101-106)
                env = container.setdefault("env", [])
                existing = {e.get("name") for e in env}
                for k, v in acc.env_vars.items():
                    if k not in existing:
                        env.append({"name": k, "value": v})


def tpu_chips_requested(template: Dict[str, Any] | None) -> int:
    """Total ``cloud-tpus.google.com/*`` chips requested by a pod template
    (TPU-native; the analogue of the reference's GPU-resource scan,
    helpers.go:62-83)."""
    total = 0
    pod_spec = (template or {}).get("spec") or {}
    for container in pod_spec.get("containers") or []:
        resources = container.get("resources") or {}
        merged: Dict[str, Any] = {}
        merged.update(resources.get("requests") or {})
        merged.update(resources.get("limits") or {})  # limits win, like kube
        for res_name, qty in merged.items():
            if res_name.startswith(TPU_RESOURCE_PREFIX):
                try:
                    total += int(qty)
                except (TypeError, ValueError):
                    pass
    return total
