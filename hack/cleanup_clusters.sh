#!/usr/bin/env bash
# Delete all TPUJobs and any orphaned child resources.
#
# Reference parity: hack/scripts/cleanup_clusters.sh:5-7 — which used the
# stale upstream selector `kubeflow.org=` while the fork actually labeled
# children with `fioravanzo.org=` (SURVEY.md "quirks to fix, not copy").
# Fixed here: the selector matches the label the operator really stamps
# (tpu_operator/trainer/labels.py: tpuoperator.dev=).
set -euo pipefail

NAMESPACE="${1:-default}"

kubectl -n "${NAMESPACE}" delete tpujobs --all --ignore-not-found
kubectl -n "${NAMESPACE}" delete pods,services -l tpuoperator.dev= --ignore-not-found
