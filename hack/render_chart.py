#!/usr/bin/env python3
"""Render the deploy/chart templates without helm.

``python hack/render_chart.py | kubectl apply -f -`` is the helm-free
install path (the reference only offered ``helm install``,
README.md:28-47). Supports exactly the template subset the chart uses:

- ``{{ .Values.path.to.key }}`` / ``{{ .Release.Namespace }}`` substitution
- ``{{- if .Values.x }}`` / ``{{- if and .Values.x .Values.y }}`` …
  ``{{- end }}`` blocks (truthiness)
- ``{{- .Values.x | toYaml | nindent N }}``

Also imported by tests/test_manifests.py to assert every rendered template
is valid YAML with the expected objects.
"""

from __future__ import annotations

import pathlib
import re
import sys
from typing import Any, Dict, List

import yaml

CHART_DIR = (
    pathlib.Path(__file__).resolve().parent.parent
    / "deploy" / "chart" / "tpu-job-operator-chart"
)

_IF_RE = re.compile(r"^\s*\{\{-\s*if\s+(.+?)\s*\}\}\s*$")
_END_RE = re.compile(r"^\s*\{\{-\s*end\s*\}\}\s*$")
_NINDENT_RE = re.compile(
    r"^(\s*)\{\{-\s*(\S+)\s*\|\s*toYaml\s*\|\s*nindent\s+(\d+)\s*\}\}\s*$"
)
_SUBST_RE = re.compile(r"\{\{\s*([^}|]+?)\s*\}\}")


def _lookup(expr: str, values: Dict[str, Any], namespace: str) -> Any:
    expr = expr.strip()
    if expr == ".Release.Namespace":
        return namespace
    if not expr.startswith(".Values."):
        raise ValueError(f"unsupported template expression: {expr!r}")
    node: Any = values
    for part in expr[len(".Values."):].split("."):
        if not isinstance(node, dict) or part not in node:
            raise KeyError(f"values key not found: {expr}")
        node = node[part]
    return node


def render(text: str, values: Dict[str, Any], namespace: str = "default") -> str:
    out: List[str] = []
    # Stack of bools: is the current if-block emitting?
    emitting = [True]
    for line in text.splitlines():
        m = _IF_RE.match(line)
        if m:
            cond = m.group(1).split()
            exprs = cond[1:] if cond[0] == "and" else cond
            truthy = all(bool(_lookup(e, values, namespace)) for e in exprs)
            emitting.append(emitting[-1] and truthy)
            continue
        if _END_RE.match(line):
            if len(emitting) == 1:
                raise ValueError("unbalanced {{- end }}")
            emitting.pop()
            continue
        if not emitting[-1]:
            continue
        m = _NINDENT_RE.match(line)
        if m:
            _prefix, expr, n = m.group(1), m.group(2), int(m.group(3))
            dumped = yaml.safe_dump(
                _lookup(expr, values, namespace), default_flow_style=False
            ).rstrip("\n")
            # nindent chomps the preceding newline via {{- and prepends its own.
            pad = " " * n
            out.extend(pad + ln for ln in dumped.splitlines())
            continue
        out.append(
            _SUBST_RE.sub(
                lambda m: str(_lookup(m.group(1), values, namespace)), line
            )
        )
    if len(emitting) != 1:
        raise ValueError("unclosed {{- if }}")
    return "\n".join(out) + "\n"


def render_chart(namespace: str = "default",
                 include_tests: bool = False) -> Dict[str, str]:
    """template-relative-path → rendered text, for every chart template."""
    with open(CHART_DIR / "values.yaml", encoding="utf-8") as f:
        values = yaml.safe_load(f)
    rendered: Dict[str, str] = {}
    for path in sorted((CHART_DIR / "templates").rglob("*.yaml")):
        rel = str(path.relative_to(CHART_DIR / "templates"))
        if rel.startswith("tests/") and not include_tests:
            continue
        rendered[rel] = render(path.read_text(encoding="utf-8"), values, namespace)
    return rendered


def main() -> int:
    namespace = sys.argv[1] if len(sys.argv) > 1 else "default"
    docs = render_chart(namespace)
    print("\n---\n".join(docs[k] for k in sorted(docs)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
