#!/usr/bin/env python3
"""Image-completeness gate: every import reachable from the shipped images
must resolve from their pinned requirements.

Reference parity: the reference's image build compiled its one binary with
all deps installed (build/images/mx_operator/Dockerfile:22-28), so a missing
dependency failed at *build* time. The Python images have no compile step,
so a payload module importing something the image never installs (the
round-1 orbax bug: payload/checkpoint.py imported orbax.checkpoint while the
Dockerfile installed only jax/flax/optax/pyyaml) only explodes at *job
startup*. This script closes that hole statically + dynamically:

1. Static: walk every module shipped in each image, parse its imports with
   ``ast``, and assert each top-level import is stdlib, in-repo, or covered
   by that image's requirements.txt.
2. Dynamic: import every payload module in the dev environment, so a broken
   module body (not just a missing dep) fails CI.

Run from hack/verify.sh. Exits non-zero with a per-module report on failure.
"""

from __future__ import annotations

import ast
import importlib
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "tpu_operator"

# requirement-name -> import names it provides. Keep in lockstep with
# build/images/*/requirements.txt.
REQUIREMENT_PROVIDES = {
    "jax": {"jax", "jaxlib"},
    "flax": {"flax"},
    "optax": {"optax"},
    "orbax-checkpoint": {"orbax"},
    "numpy": {"numpy"},
    "pyyaml": {"yaml"},
}

# Imports allowed to be missing from the image because the code gates them
# behind a feature flag AND degrades cleanly (must be justified here).
OPTIONAL_IMPORTS: dict[str, set[str]] = {
    # none currently — checkpoint.py's orbax import is mandatory by design:
    # a checkpointDir job that cannot checkpoint must die loudly at startup,
    # so orbax ships in the image instead of being optional.
}


def parse_requirements(path: pathlib.Path) -> set[str]:
    provided: set[str] = set()
    for line in path.read_text().splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        name = re.split(r"[\[=<>!~;]", line, 1)[0].strip().lower()
        provided |= REQUIREMENT_PROVIDES.get(name, {name.replace("-", "_")})
    return provided


def module_imports(path: pathlib.Path) -> set[str]:
    tree = ast.parse(path.read_text(), filename=str(path))
    tops: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            tops |= {alias.name.split(".")[0] for alias in node.names}
        elif isinstance(node, ast.ImportFrom) and node.level == 0 and node.module:
            tops.add(node.module.split(".")[0])
    return tops


def check_image(label: str, files: list[pathlib.Path], reqs: pathlib.Path) -> list[str]:
    provided = parse_requirements(reqs)
    failures = []
    for f in sorted(files):
        rel = f.relative_to(REPO)
        for top in sorted(module_imports(f)):
            if top in sys.stdlib_module_names or top == "tpu_operator":
                continue
            if top in provided or top in OPTIONAL_IMPORTS.get(str(rel), set()):
                continue
            failures.append(
                f"{label}: {rel} imports '{top}' which {reqs.name} does not install"
            )
    return failures


def check_pyproject_lockstep() -> list[str]:
    """The pin list lives in three places (pyproject 'payload' extra + the
    two image requirements.txt); assert the pyproject extra stays in
    lockstep with the payload image so `pip install .[payload]` cannot
    silently diverge from the shipped image."""
    import tomllib

    with open(REPO / "pyproject.toml", "rb") as f:
        proj = tomllib.load(f)

    def pins(lines: list[str]) -> dict[str, str]:
        out = {}
        for line in lines:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            name = re.split(r"[\[=<>!~;]", line, 1)[0].strip().lower()
            ver = line.split("==", 1)[1].strip() if "==" in line else ""
            out[name.replace("-", "_")] = ver
        return out

    img = pins((REPO / "build/images/tpu_payload/requirements.txt")
               .read_text().splitlines())
    extra = pins(proj["project"]["optional-dependencies"]["payload"])
    failures = []
    for name, ver in extra.items():
        if img.get(name) != ver:
            failures.append(
                f"pin drift: pyproject payload extra has {name}=={ver} but "
                f"payload image requirements.txt has {img.get(name, 'nothing')}")
    for name, ver in img.items():
        if name not in extra:
            failures.append(
                f"pin drift: payload image requirements.txt has {name}=={ver} "
                f"but the pyproject payload extra omits it")
    return failures


def main() -> int:
    payload_files = list((PKG / "payload").glob("*.py"))
    # The operator image ships the whole package but only the control plane
    # runs in it; payload modules execute in the payload image.
    operator_files = [
        f for f in PKG.rglob("*.py") if "payload" not in f.parts
    ]

    failures = check_image(
        "payload-image", payload_files,
        REPO / "build/images/tpu_payload/requirements.txt",
    )
    failures += check_image(
        "operator-image", operator_files,
        REPO / "build/images/tpu_operator/requirements.txt",
    )
    failures += check_pyproject_lockstep()

    # Dynamic tier: the dev env has the payload deps, so a module that cannot
    # even import (syntax error, bad module-level code, renamed dep) fails
    # here rather than at job startup.
    sys.path.insert(0, str(REPO))
    for f in sorted(payload_files):
        mod = "tpu_operator.payload." + f.stem if f.stem != "__init__" \
            else "tpu_operator.payload"
        try:
            importlib.import_module(mod)
        except Exception as exc:  # noqa: BLE001 — report all import failures
            failures.append(f"import {mod}: {type(exc).__name__}: {exc}")

    if failures:
        print("check_payload_image: FAIL")
        for line in failures:
            print(f"  {line}")
        return 1
    print(f"check_payload_image: OK "
          f"({len(payload_files)} payload + {len(operator_files)} operator modules)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
