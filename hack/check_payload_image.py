#!/usr/bin/env python3
"""Image-completeness gate — thin shim over the shared analysis driver.

The implementation moved to ``tpu_operator/analysis/payload_image.py`` so
all contract checks share one runner, finding format, and allowlist
(``python hack/analyze.py`` runs it alongside the other five rules; this
entry point remains for muscle memory and older scripts)::

    python hack/check_payload_image.py
    # == python hack/analyze.py --rules payload-image
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pathlib import Path  # noqa: E402

from tpu_operator.analysis.driver import run_analysis  # noqa: E402


def main() -> int:
    active, _suppressed, stale = run_analysis(
        Path(REPO), rules=["payload-image"])
    if active or stale:
        print("check_payload_image: FAIL")
        for finding in active:
            print(f"  {finding.render()}")
        for rule, key in sorted(stale):
            print(f"  stale allowlist entry (delete it): {rule}  {key}")
        return 1
    print("check_payload_image: OK (via tpu_operator/analysis)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
