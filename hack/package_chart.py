#!/usr/bin/env python3
"""Package the Helm chart into a versioned tarball (helm-package parity).

The reference ships its chart as a committed artifact
(``build/chart/mx-job-operator-chart-0.1.0.tgz``); this writes the
equivalent ``build/chart/tpu-job-operator-chart-<version>.tgz`` (version
read from Chart.yaml) with a byte-reproducible tar: sorted member order,
zeroed timestamps/uids, fixed gzip header — so the committed artifact is
a pure function of the chart sources and ``--check`` can gate drift in
hack/verify.sh exactly like the CRD and lockfile generators.
"""

from __future__ import annotations

import argparse
import gzip
import io
import pathlib
import sys
import tarfile

import yaml

REPO = pathlib.Path(__file__).resolve().parent.parent
CHART_DIR = REPO / "deploy" / "chart" / "tpu-job-operator-chart"
OUT_DIR = REPO / "build" / "chart"


def chart_version() -> str:
    with open(CHART_DIR / "Chart.yaml", encoding="utf-8") as f:
        return str(yaml.safe_load(f)["version"])


def build_tgz_bytes() -> bytes:
    """Deterministic .tgz of the chart, members prefixed with the chart
    name (helm's layout)."""
    tar_buf = io.BytesIO()
    with tarfile.open(fileobj=tar_buf, mode="w", format=tarfile.USTAR_FORMAT) as tar:
        for path in sorted(CHART_DIR.rglob("*")):
            if not path.is_file():
                continue
            rel = f"{CHART_DIR.name}/{path.relative_to(CHART_DIR)}"
            info = tarfile.TarInfo(rel)
            data = path.read_bytes()
            info.size = len(data)
            info.mtime = 0
            info.uid = info.gid = 0
            info.uname = info.gname = ""
            info.mode = 0o644
            tar.addfile(info, io.BytesIO(data))
    gz_buf = io.BytesIO()
    with gzip.GzipFile(fileobj=gz_buf, mode="wb", mtime=0) as gz:
        gz.write(tar_buf.getvalue())
    return gz_buf.getvalue()


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--check", action="store_true",
                   help="fail if the committed artifact differs from the "
                        "chart sources (drift gate)")
    args = p.parse_args(argv)

    out = OUT_DIR / f"{CHART_DIR.name}-{chart_version()}.tgz"
    data = build_tgz_bytes()
    if args.check:
        if not out.exists():
            print(f"package_chart: {out} missing — run "
                  f"`python hack/package_chart.py`", file=sys.stderr)
            return 1
        if out.read_bytes() != data:
            print(f"package_chart: {out} is stale vs deploy/chart — run "
                  f"`python hack/package_chart.py`", file=sys.stderr)
            return 1
        print(f"package_chart: {out.relative_to(REPO)} up to date")
        return 0
    OUT_DIR.mkdir(parents=True, exist_ok=True)
    out.write_bytes(data)
    print(f"wrote {out.relative_to(REPO)} ({len(data)} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
