#!/usr/bin/env python3
"""End-to-end smoke test for the TPU job operator.

Reference parity: the reference's helm chart shipped a test hook that ran an
e2e binary it never included (build/chart/mx-job-operator-chart/templates/
tests/basic-test.yaml:17-22, SURVEY.md §4 "binary is not in repo"). This is
that missing binary, in both deployment modes:

- **local** (default): no cluster needed. Starts the in-process apiserver
  (tpu_operator.testing.apiserver), runs the REAL operator entry path
  (cmd.server.run — leader election, informers, controller) against it over
  HTTP, submits ``examples/tpujob-linear.yml``, plays kubelet by walking pod
  statuses Pending → Running → Succeeded, and asserts the job phase reaches
  Running and then Done with state Succeeded.
- **--in-cluster**: runs inside the helm-test pod against the live
  apiserver; submits the example and polls until the operator (already
  deployed) drives it to Succeeded.

Exit 0 on pass, 1 on fail — the helm test contract.
"""

from __future__ import annotations

import argparse
import pathlib
import sys
import threading
import time

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))


def load_example(path: pathlib.Path) -> dict:
    import yaml

    with open(path, encoding="utf-8") as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    assert len(docs) == 1, f"{path} must contain exactly one TPUJob"
    return docs[0]


def wait_for(predicate, timeout: float, interval: float = 0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(interval)
    return None


def job_phase_state(cs, namespace: str, name: str) -> tuple:
    try:
        job = cs.tpujobs.get(namespace, name)
    except Exception:
        return ("", "")
    status = job.get("status") or {}
    return (status.get("phase", ""), status.get("state", ""))


def play_kubelet(cs, namespace: str, stop: threading.Event,
                 succeed_after: float) -> None:
    """Walk every managed pod Pending → Running, then (after
    ``succeed_after`` seconds of Running) → Succeeded with a clean exit —
    the container lifecycle kubelet would produce for a passing payload."""
    started: dict = {}
    while not stop.is_set():
        try:
            pods = cs.pods.list(namespace)
        except Exception:
            time.sleep(0.2)
            continue
        now = time.monotonic()
        for pod in pods:
            name = pod["metadata"]["name"]
            phase = (pod.get("status") or {}).get("phase", "")
            if phase in ("", "Pending"):
                pod["status"] = {
                    "phase": "Running",
                    "containerStatuses": [
                        {"name": "tpu", "state": {"running": {}}}
                    ],
                }
                started[name] = now
                cs.pods.update_status(namespace, pod)
            elif phase == "Running" and now - started.get(name, now) >= succeed_after:
                pod["status"] = {
                    "phase": "Succeeded",
                    "containerStatuses": [
                        {"name": "tpu",
                         "state": {"terminated": {"exitCode": 0}}}
                    ],
                }
                cs.pods.update_status(namespace, pod)
        time.sleep(0.2)


def run_local(example: pathlib.Path, timeout: float) -> int:
    from tpu_operator.client.rest import Clientset, RestConfig
    from tpu_operator.cmd import server
    from tpu_operator.cmd.options import build_parser
    from tpu_operator.testing.apiserver import ApiServerHarness

    job = load_example(example)
    namespace = job["metadata"].get("namespace", "default")
    name = job["metadata"]["name"]

    with ApiServerHarness() as srv:
        opts = build_parser().parse_args([
            "--master", srv.url, "--namespace", namespace,
            "--resync-period", "2", "--gc-interval", "3600",
        ])
        stop = threading.Event()
        operator = threading.Thread(target=server.run, args=(opts,),
                                    kwargs={"stop_event": stop}, daemon=True)
        operator.start()
        cs = Clientset(RestConfig(host=srv.url, timeout=5.0))
        kubelet = threading.Thread(target=play_kubelet,
                                   args=(cs, namespace, stop, 2.0), daemon=True)
        kubelet.start()
        try:
            cs.tpujobs.create(namespace, job)
            ok_running = wait_for(
                lambda: job_phase_state(cs, namespace, name)[0] == "Running",
                timeout)
            if not ok_running:
                print(f"FAIL: job never reached Running "
                      f"(at {job_phase_state(cs, namespace, name)})")
                return 1
            print("job reached phase Running")
            ok_done = wait_for(
                lambda: job_phase_state(cs, namespace, name)
                == ("Done", "Succeeded"), timeout)
            if not ok_done:
                print(f"FAIL: job never reached Done/Succeeded "
                      f"(at {job_phase_state(cs, namespace, name)})")
                return 1
            pods = cs.pods.list(namespace)
            print(f"PASS: {name} Done/Succeeded; {len(pods)} pod(s) retained "
                  f"for log inspection")
            return 0
        finally:
            stop.set()
            operator.join(timeout=10.0)


def run_in_cluster(example: pathlib.Path, timeout: float) -> int:
    from tpu_operator.client.rest import Clientset
    from tpu_operator.util import k8sutil
    from tpu_operator.util.util import get_operator_namespace

    job = load_example(example)
    namespace = job["metadata"].get("namespace") or get_operator_namespace()
    name = job["metadata"]["name"]
    cs = Clientset(k8sutil.get_cluster_config("", ""))
    try:
        cs.tpujobs.delete(namespace, name)
    except Exception:
        pass
    cs.tpujobs.create(namespace, job)
    ok = wait_for(
        lambda: job_phase_state(cs, namespace, name) == ("Done", "Succeeded"),
        timeout, interval=2.0)
    phase, state = job_phase_state(cs, namespace, name)
    print(f"{'PASS' if ok else 'FAIL'}: {name} phase={phase} state={state}")
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--in-cluster", action="store_true",
                   help="run against the live apiserver (helm-test mode)")
    p.add_argument("--example",
                   default=str(REPO_ROOT / "examples" / "tpujob-linear.yml"))
    p.add_argument("--timeout", type=float, default=60.0)
    args = p.parse_args(argv)
    example = pathlib.Path(args.example)
    if args.in_cluster:
        return run_in_cluster(example, args.timeout)
    return run_local(example, args.timeout)


if __name__ == "__main__":
    sys.exit(main())
