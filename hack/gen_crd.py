#!/usr/bin/env python3
"""Render the TPUJob CRD manifests from the structural schema source of
truth (tpu_operator/apis/tpujob/v1alpha1/schema.py) into

    examples/crd.yml
    deploy/chart/tpu-job-operator-chart/templates/crd.yaml  (Helm-wrapped)

Run with ``--check`` (hack/verify.sh does) to fail on drift instead of
writing — the schema-in-code and the YAML on disk can then never diverge,
the same guarantee the reference got from hack/verify-codegen.sh for its
generated clients.
"""

from __future__ import annotations

import argparse
import os
import sys

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpu_operator.apis.tpujob.v1alpha1 import schema as schema_mod  # noqa: E402

HEADER = """\
# TPUJob CustomResourceDefinition.
#
# Reference parity: examples/crd.yml:1-11 (the reference registers an
# apiextensions/v1beta1 CRD for mxjobs.fioravanzo.org). This is the modern
# apiextensions/v1 equivalent for tpujobs.tpuoperator.dev with a structural
# openAPIV3Schema GENERATED from tpu_operator/apis/tpujob/v1alpha1/schema.py
# by hack/gen_crd.py — do not edit the schema here. The PodTemplateSpec
# subtree stays permissive (x-kubernetes-preserve-unknown-fields), keeping
# the reference's "don't hide Kubernetes" passthrough; everything else is
# typed, enum-bounded, and unknown-field-free.
"""

CHART_HEADER = """\
# Reference parity: build/chart/mx-job-operator-chart/templates/crd.yaml
# Schema GENERATED from tpu_operator/apis/tpujob/v1alpha1/schema.py by
# hack/gen_crd.py — do not edit the schema here (hack/verify.sh checks
# drift).
"""


def crd_dict() -> dict:
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "tpujobs.tpuoperator.dev"},
        "spec": {
            "group": "tpuoperator.dev",
            "scope": "Namespaced",
            "names": {
                "kind": "TPUJob",
                "singular": "tpujob",
                "plural": "tpujobs",
                "shortNames": ["tj"],
            },
            "versions": [{
                "name": "v1alpha1",
                "served": True,
                "storage": True,
                "schema": {
                    "openAPIV3Schema":
                        schema_mod.tpujob_openapi_v3_schema(),
                },
                "subresources": {"status": {}},
                "additionalPrinterColumns": [
                    {"name": "Phase", "type": "string",
                     "jsonPath": ".status.phase"},
                    {"name": "State", "type": "string",
                     "jsonPath": ".status.state"},
                    {"name": "Attempt", "type": "integer",
                     "jsonPath": ".status.attempt"},
                    {"name": "Age", "type": "date",
                     "jsonPath": ".metadata.creationTimestamp"},
                ],
            }],
        },
    }


def render_example() -> str:
    return HEADER + yaml.safe_dump(crd_dict(), sort_keys=False,
                                   default_flow_style=False)


def render_chart() -> str:
    body = yaml.safe_dump(crd_dict(), sort_keys=False,
                          default_flow_style=False)
    return (CHART_HEADER + "{{- if .Values.crd.install }}\n" + body
            + "{{- end }}\n")


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--check", action="store_true",
                   help="fail on drift instead of writing")
    args = p.parse_args()

    targets = {
        os.path.join(REPO, "examples/crd.yml"): render_example(),
        os.path.join(REPO, "deploy/chart/tpu-job-operator-chart/templates/"
                           "crd.yaml"): render_chart(),
    }
    drifted = []
    for path, want in targets.items():
        have = open(path).read() if os.path.exists(path) else ""
        if have != want:
            if args.check:
                drifted.append(path)
            else:
                with open(path, "w") as f:
                    f.write(want)
                print(f"gen_crd: wrote {os.path.relpath(path, REPO)}")
    if drifted:
        print("gen_crd: DRIFT — regenerate with `python hack/gen_crd.py`:")
        for path in drifted:
            print(f"  {os.path.relpath(path, REPO)}")
        return 1
    if args.check:
        print("gen_crd: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
