"""Microbench the flash-attention kernels at the flagship attention shape.

Times forward-only and forward+backward at the exact per-microbatch shape
the flagship LM runs (B8 T2048 H16 KV4 D128 by default), reporting ms and
effective TFLOPS (causal halves the realized MACs; fwd = 2 tile matmuls,
bwd = 6). This is the tool behind docs/benchmarks.md's attention-bucket
numbers: run it before and after kernel changes.

Timing methodology — long windows only. The axon tunnel pays a large
dispatch-latency ramp after every fence (measured ~115 ms across the
first ~15 steps of a window: the host streams dispatches one RTT at a
time until the async queue covers the round trip). Short windows are
therefore dominated by dispatch latency and *invert* kernel rankings —
a 10-step window measured this kernel at 9.6 ms/step where the 200-step
steady state is 2.8 ms. Real training never pays this (the train loop
dispatches continuously), so steady state is the honest number. Default:
150-step windows, median of 3.

Usage: python hack/attn_microbench.py [--t 2048] [--b 8] [--heads 16]
       [--kv 4] [--d 128] [--steps 150] [--windows 3] [--no-causal]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--t", type=int, default=2048)
    p.add_argument("--b", type=int, default=8)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--kv", type=int, default=4)
    p.add_argument("--d", type=int, default=128)
    p.add_argument("--steps", type=int, default=150)
    p.add_argument("--windows", type=int, default=3)
    p.add_argument("--no-causal", action="store_true")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from tpu_operator.payload import flash_attention as fa

    causal = not args.no_causal
    key = jax.random.key(0)
    mk = lambda hh: jax.random.normal(
        key, (args.b, args.t, hh, args.d), jnp.bfloat16)
    q, k, v = mk(args.heads), mk(args.kv), mk(args.kv)

    fwd = jax.jit(lambda q, k, v: fa.flash_attention(q, k, v, causal=causal))
    grad = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(
            fa.flash_attention(q, k, v, causal=causal).astype(jnp.float32)
            ** 2),
        argnums=(0, 1, 2)))

    frac = 0.5 if causal else 1.0
    mm = 2 * args.b * args.heads * args.t * args.t * args.d * frac
    fwd_flops = 2 * mm
    bwd_flops = 6 * mm

    def timed(fn, tag, flops):
        val = None
        for _ in range(10):
            val = fn(q, k, v)
        jax.device_get(jax.tree_util.tree_leaves(val)[0].ravel()[0])
        times = []
        for _ in range(args.windows):
            t0 = time.perf_counter()
            for _ in range(args.steps):
                val = fn(q, k, v)
            jax.device_get(jax.tree_util.tree_leaves(val)[0].ravel()[0])
            times.append((time.perf_counter() - t0) / args.steps)
        times.sort()
        med = times[len(times) // 2]
        spread = 100 * (times[-1] - times[0]) / med if len(times) > 1 else 0.0
        print(f"{tag:24s} {med * 1e3:8.2f} ms   "
              f"{flops / med / 1e12:7.1f} TFLOPS eff   "
              f"spread {spread:.1f}%")
        return med

    print(f"shape B{args.b} T{args.t} H{args.heads} KV{args.kv} D{args.d} "
          f"causal={causal} backend={jax.default_backend()} "
          f"steps/window={args.steps}")
    f = timed(fwd, "forward", fwd_flops)
    fb = timed(grad, "forward+backward", fwd_flops + bwd_flops)
    print(f"{'backward (derived)':24s} {(fb - f) * 1e3:8.2f} ms   "
          f"{bwd_flops / (fb - f) / 1e12:7.1f} TFLOPS eff")
    return 0


if __name__ == "__main__":
    sys.exit(main())
