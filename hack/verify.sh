#!/usr/bin/env bash
# CI gate: unit tests + example-manifest validation + local e2e smoke.
#
# Reference parity: hack/verify-codegen.sh (the reference's only CI check was
# client-codegen drift; its unit tests did not compile — SURVEY.md §4). This
# fork has no generated code to drift, so the gate is the test pyramid
# itself.
set -euo pipefail

cd "$(dirname "$0")/.."

# Contract-analysis gate, first and fail-fast: the per-job state
# lifecycle contract (# per-job: annotations — every job-keyed
# container declares + proves its removal path, every job-identity
# metric family has a deletion prune site), spec-drift across
# types/schema/defaults/validation/CRD, the env-var contract between
# trainer/replicas.py and the payload, the heartbeat-key chain, lock
# discipline (# guarded-by annotations), the cross-module lock-order
# graph (cycles = potential deadlocks, blocking calls one hop below a
# lock), escape analysis (unguarded state shared across thread
# entrypoints), exception policy, and the payload-image import check.
# Cheaper than any test and catches the cross-file drift tests can't.
python hack/analyze.py

# Runtime lockdep witness ON for the whole test pyramid below (and the
# subprocess payloads the e2es spawn): every lock the operator creates
# is order-instrumented, so the chaos soak and fleet gates double as
# deadlock detectors; a lock-order inversion fails the owning test with
# both witness stacks. Zero overhead outside verify (factories return
# raw threading primitives when unset).
export TPUJOB_LOCKDEP=1
# Job-lifecycle witness ON the same way: every `# per-job:` container
# constructs through joblife.track, the controller's deletion reconcile
# sweeps the registry + the metric registry, and the conftest guard
# fails any test on whose watch per-job state outlived a deleted job.
export TPUJOB_JOBLIFE=1

# The witnesses' own contracts, then the deterministic interleaving
# harness + the four seeded-schedule races (fleet admission/release/
# rebuild, writeback defer/critical bypass, straggler fold/attempt
# reset, write-behind enqueue/close-drain) — standalone so a
# concurrency regression fails by name, before the broad suites.
python -m pytest tests/test_lockdep.py -x -q
# The lifecycle contract's own suite: rule fixtures with seeded
# violations, the joblife witness, and the deletion-prune regressions.
python -m pytest tests/test_lifecycle.py -x -q
python -m pytest tests/test_schedules.py -x -q
# Lint gate (pinned in the pyproject `dev` extra). Skipped with a warning
# when ruff is not installed — the stdlib-only analyzer above always runs.
if command -v ruff >/dev/null 2>&1; then
  ruff check tpu_operator/ tests/ hack/ bench.py
else
  echo "verify: WARNING — ruff not installed (pip install -e .[dev]); lint skipped"
fi
python hack/gen_lock.py --check
# Manifests-in-sync: the CRD-YAML drift check (`gen_crd.py --check`) is
# owned by the analyzer's spec-drift rule above — not repeated here; the
# chart package check has no analyzer home yet.
python hack/package_chart.py --check
# Standalone observability gate: every /metrics line must parse as valid
# Prometheus exposition format (HELP/TYPE, label escaping, bucket
# monotonicity, _sum/_count consistency) with deterministic-clock
# histograms — run first so a telemetry regression fails fast and alone.
python -m pytest tests/test_metrics_conformance.py -x -q
# Standalone robustness gate: the chaos soak (level-1 pod kills + 10% flaky
# API against the in-process apiserver, seeded RNG) must drive a
# checkpointed, twice-preempted job to DONE through the Backoff phase with
# no leaked pods — the whole time-aware recovery stack under fire.
python -m pytest tests/test_chaos_soak.py -x -q
# Standalone durability gate: the checkpoint chaos test (a worker SIGKILLed
# mid-save, the latest checkpoint corrupted, seeded RNG, real subprocess
# payloads over the in-process apiserver) must resume from the last
# VERIFIED step — never step 0 — and reach DONE, with lastCheckpointStep
# in job status and the restore-fallback counter incremented.
python -m pytest tests/test_checkpoint_chaos.py -x -q
# The measured form of the durable path: verified-save/restore latency and
# the corrupt-latest fallback-scan cost must at least run clean.
python bench.py --checkpoint --quick
# Standalone warm-restart gate: the compilationCache spec wiring, the
# overlapped restore+compile prologue (PR 4 restore semantics preserved),
# startup-stage heartbeats, and the status.startup/metrics fold.
python -m pytest tests/test_startup_path.py -x -q
# And its measured form: a warm restart (persistent compilation cache hit
# + overlapped prologue) must beat cold time-to-first-step by the budget
# factor, with steady-state step time held — exits nonzero otherwise.
python bench.py --startup --quick
# Standalone remote warm-start store gate: blob backends + chunked
# integrity transfer (torn-upload resume, checksum-retry, next-oldest
# fallback), the spec.store wiring, write-behind upload + escalation,
# quarantine parity (local corrupt step never re-preferred remotely),
# rendezvous-overlapped prefetch, and the status.store/goodput folds.
python -m pytest tests/test_store.py -x -q
# And its measured form: a fresh-node restart (cold local dirs, warm
# remote store) must beat a fully cold start by the budget factor with
# the prefetch hit + goodput asserted, and the write-behind must stay
# off the step loop — exits nonzero otherwise.
python bench.py --store --quick
# Standalone data-plane observability gate: the step flight recorder
# (phase laps, ring buffer, windowed digests, postmortem dump), the
# stepTiming heartbeat chain through statusserver sanitization and the
# controller fold, and gang straggler detection (slowed replica flagged
# into status.stragglers + StragglerDetected + describe + /metrics).
python -m pytest tests/test_steptrace.py -x -q
# And its measured form: recorder-on steady step time must stay within
# 1% of recorder-off (50 µs absolute floor) — the near-zero-cost claim
# as an enforced budget, exits nonzero on regression.
python bench.py --steptrace --quick
# Standalone self-tuning data-plane gate: the autotune controller (hill
# climb, hysteresis, regression backoff, clamps), dynamic prefetch-depth
# resize (byte-identical stream order), the background host pipeline,
# the async host path, the spec.dataPlane/env wiring, and the dataPlane
# heartbeat chain (sanitization → status fold → metrics → describe).
python -m pytest tests/test_autotune.py -x -q
# And its measured form: the autotuner must converge within 5% of the
# best static prefetch depth inside the window budget, the async host
# path must shave measured HOST-phase time, and recorder+autotune must
# hold the 1% overhead budget — exits nonzero on regression.
python bench.py --dataplane --quick
# Standalone flagship compute-path gate: the shared option surface
# (payload/compute.py — remat policy, sgd/adam/adam8, fused loss,
# scan-over-blocks, AOT through the persistent cache), numerics parity
# between the seed and optimized paths at a fixed seed, option
# round-trips for the classifier AND the LM parsers, and checkpoint
# resume ACROSS the path flip through the PR-4 verified walk.
python -m pytest tests/test_flagship_compute.py -x -q
# And its measured form: each option A/B'd individually against the
# seed path in interleaved windows (min-of-pairwise-delta, PR-9
# discipline) with per-option regression budgets, plus the
# autotune-engaged residue row attributing the remaining gap to a
# named phase — exits nonzero when an option regresses past budget.
python bench.py --flagship --quick
# Standalone serving-mode gate: spec.mode serve end to end — the
# mode/serving spec wiring, readiness-gated per-replica Services (no
# endpoints before the ready beat; removed and restored around a
# reload), the serving heartbeat chain (sanitization → controller fold
# → status.serving → metrics → describe), traffic-driven replica
# scaling through the fleet scheduler, and the hot-reload acceptance
# e2e (loadedStep advances, attempt does not).
python -m pytest tests/test_serving.py -x -q
# Standalone paged-KV-cache gate: the block-paged decode engine —
# allocator invariants (alloc/free/reuse, double-free raises), the
# paged decode path bit-equal to the dense re-forward at a fixed seed,
# admission churn with page reuse, oversubscribed-pool backpressure,
# and hot reload swapping params without invalidating live pages.
python -m pytest tests/test_kvcache.py -x -q
# And the measured form: the continuous-batching decode service under
# the synthetic load generator (p99 under the SLO budget, zero shed,
# zero failed decode steps), the rolling reload under sustained load,
# the incremental-vs-reforward A/B, and the flat-per-token-cost gate —
# any regression exits nonzero.
python bench.py --serve --quick
# Standalone elastic-gangs gate: inventory-sized attempts (grant in
# [minSlices, maxSlices], shrink-don't-queue, re-expand, granted — not
# spec — accounting), the reshard-aware restore through the remote
# store, straggler remediation (replace without budget / shed one slice
# on the preemption budget), and the acceptance e2es over the
# in-process apiserver.
python -m pytest tests/test_elastic.py -x -q
# Standalone lifecycle gate, measured form: >=200 create-run-delete
# cycles through the real operator with the joblife witness on — any
# per-job container or metric series outliving a deleted job, any
# /metrics series-count growth, or RSS growth past budget exits
# nonzero (ROADMAP item 5's "no leaked metric series and bounded
# memory", enforced per PR).
python bench.py --churn --quick
# Standalone fleet-scheduler gate: slice-inventory admission (whole-gang
# fit or phase Queued), fair-share + priority ordering, preemption victim
# selection + the preemption-budget requeue, inventory release on
# teardown/TTL, rebuild-from-cache after operator restart, shard-affinity
# (one key never reconciles concurrently), and the writeback limiter.
python -m pytest tests/test_fleet_scheduler.py -x -q
# And the measured form: a few hundred jobs through the admission queue
# over the in-process apiserver (sharded workers, kubelet sim) with p99
# reconcile latency, the status-PUT budget, and the PR-3 zero-read steady
# state asserted at fleet scale — exits nonzero on regression.
python bench.py --fleet --quick
# Standalone fake-cluster gate: node/kubelet state machines (bind →
# ContainerCreating → Running/heartbeats → terminal), kubelet-level
# preemption shape, seeded storm-plan determinism (same seed →
# bit-identical schedule), the inventory flap-debounce regression, and
# the chaos-composition soak (FlakyClientset × pod kills × blob faults
# with preemption-kind-only ledger records).
python -m pytest tests/test_fake_cluster.py -x -q
# And the measured form: ~1k pods / 500 jobs through the REAL operator
# over the in-process apiserver while a seeded storm lands mid-flight
# (slice preemption sweeps, node flaps inside the debounce window, an
# API-fault burst, slow kubelets, a drain). Gates: full drain, zero
# leaked pods / stuck Queued / joblife residue, flat metric-series
# count, bounded RSS, and reconcile p99 bounded DURING the storm —
# exits nonzero on regression. Full scale (10k pods): bench.py --cluster.
python bench.py --cluster --quick
# Standalone cooperative-drain gate: the status.drain directive lifecycle
# (request → heartbeat-ACK → planned exit 160 → preemption-pool billing
# with no backoff and no crash-loop streak), stale-attempt expiry, the
# grow-debounced in-attempt live resize, drain-first eviction with the
# checkpoint-freshness skip, the maintenance cordon watch, deadline
# expiry → hard teardown, and the observability fold (metrics, describe,
# per-job series prune) over the in-process apiserver.
python -m pytest tests/test_drain.py -x -q
# And the measured form: cooperative lost-step-seconds must stay within
# one checkpoint interval (vs the hard-kill reference losing most of
# one), exactly one planned restart billed, the request→exit latency
# histogram observed, and the deadline-expiry path must still reach
# Done — exits nonzero on regression.
python bench.py --drain --quick
# Standalone control-plane budget gate: steady-state reconcile must issue
# ZERO read RPCs (all reads served by the informer indexes) and the first
# reconcile exactly N pod + N+1 service creates — a reads-per-reconcile
# regression fails CI by name, not as a slow bench row.
python -m pytest tests/test_api_budget.py -x -q
# Standalone observability gate: the unified timeline (store bounds +
# lifecycle residue, span assembly/ordering, Chrome export, fleet
# rollup fold, profile directive round-trip, ctl timeline/profile/top),
# then the same plane proven over the real operator binary and status
# port — queue→admit→preempt/resize→Done with the churn-soak residue
# check riding along.
python -m pytest tests/test_timeline.py -x -q
python -m pytest tests/test_fleet_obs_e2e.py -x -q
# And the measured form of the same contract: bench.py --control-plane
# exits nonzero if reads-per-reconcile leaves zero or the parallel gang
# create stops beating sequential (--quick: 16-32 replicas, seconds).
python bench.py --control-plane --quick
python -m pytest tests/ -x -q --ignore=tests/test_metrics_conformance.py \
  --ignore=tests/test_chaos_soak.py \
  --ignore=tests/test_checkpoint_chaos.py \
  --ignore=tests/test_api_budget.py \
  --ignore=tests/test_startup_path.py \
  --ignore=tests/test_store.py \
  --ignore=tests/test_fleet_scheduler.py \
  --ignore=tests/test_steptrace.py \
  --ignore=tests/test_autotune.py \
  --ignore=tests/test_elastic.py \
  --ignore=tests/test_serving.py \
  --ignore=tests/test_flagship_compute.py \
  --ignore=tests/test_lockdep.py \
  --ignore=tests/test_lifecycle.py \
  --ignore=tests/test_schedules.py \
  --ignore=tests/test_timeline.py \
  --ignore=tests/test_fleet_obs_e2e.py \
  --ignore=tests/test_fake_cluster.py \
  --ignore=tests/test_drain.py
python hack/e2e_smoke.py --timeout 120
echo "verify: OK"
