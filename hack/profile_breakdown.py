#!/usr/bin/env python3
"""Profile-derived step-time breakdown for a payload config.

Answers the question the bench suite's MFU numbers raise but cannot
answer: WHERE does the non-MXU time go? Captures a ``jax.profiler`` device
trace of a few steady-state steps, parses the XPlane protobuf directly
(tensorboard_plugin_profile ships the schema; no TensorBoard UI needed),
and aggregates per-op self time by the TPU runtime's ``hlo_category`` stat
(schema: tensorflow/tsl's xplane_pb2, shipped in the baked image) —
convolution/dot fusions (MXU), the Pallas attention custom-calls,
elementwise/reduce fusions (optimizer + remat recompute), infeed/outfeed,
and idle gaps (host stall) from busy-vs-wall time.

Default config = the flagship GQA bench row, so the output slots straight
into docs/benchmarks.md's attribution table:

    python hack/profile_breakdown.py            # flagship GQA, 6 steps
    python hack/profile_breakdown.py --quick    # tiny CPU smoke
"""

from __future__ import annotations

import argparse
import collections
import glob
import itertools
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FLAGSHIP = ["--dim", "2048", "--layers", "8", "--heads", "16",
            "--kv-heads", "4", "--batch", "32", "--seq-len", "2048",
            "--vocab", "32768", "--remat", "--remat-policy", "dots",
            "--grad-accum", "4", "--adam-mu-dtype", "bf16"]
QUICK = ["--dim", "64", "--layers", "2", "--heads", "2", "--batch", "4",
         "--seq-len", "128", "--vocab", "256"]


def capture(argv, steps: int, outdir: str) -> float:
    """Run warmup + ``steps`` traced steps; returns measured sec/step."""
    import jax

    from tpu_operator.payload import data as data_mod, transformer

    targs = transformer.parse_args(argv)
    mesh, _m, state, step, batches = transformer.build(targs)
    spec = transformer.lm_token_spec(mesh)
    pregen = [data_mod.put_global_batch(mesh, *b, spec=spec)
              for b in itertools.islice(batches, 4)]
    cycled = itertools.cycle(pregen)
    for _ in range(3):
        state, metrics = step(state, *next(cycled))
    jax.device_get(metrics["loss"])

    jax.profiler.start_trace(outdir)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, *next(cycled))
    jax.device_get(metrics["loss"])
    dt = (time.perf_counter() - t0) / steps
    jax.profiler.stop_trace()
    return dt


def classify(name: str, category: str) -> str:
    """hlo_category (plus name heuristics for custom calls) → report bucket."""
    cat = (category or "").lower()
    low = name.lower()
    if "custom" in cat or "custom-call" in low or "pallas" in low:
        return "attention kernels (pallas custom-calls)"
    if "convolution" in cat or cat.startswith("dot") or "matmul" in cat:
        return "matmul (MXU)"
    if "all-reduce" in cat or "all-gather" in cat or "collective" in cat \
            or "permute" in cat:
        return "collectives"
    if "infeed" in cat or "outfeed" in cat or "copy" in cat \
            or "host" in cat:
        return "data movement"
    return "elementwise / reduce / other fusions"


def parse_xplanes(outdir: str):
    """{bucket: total_self_us}, device_busy_us, plane_wall_us from every
    TPU device plane under outdir."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise SystemExit(f"no xplane.pb under {outdir}")
    buckets: dict = collections.defaultdict(float)
    busy = 0.0
    wall_lo, wall_hi = None, 0.0
    for path in paths:
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        for plane in xs.planes:
            if "TPU" not in plane.name or "XLA Ops" not in [
                    l.name for l in plane.lines]:
                if "TPU" not in plane.name:
                    continue
            ev_meta = plane.event_metadata
            st_meta = plane.stat_metadata
            for line in plane.lines:
                if line.name != "XLA Ops":
                    continue
                for ev in line.events:
                    dur = ev.duration_ps / 1e6  # ps → us
                    meta = ev_meta.get(ev.metadata_id)
                    name = meta.name if meta else ""
                    cat = ""
                    for st in ev.stats:
                        key = st_meta.get(st.metadata_id)
                        if key is not None and key.name == "hlo_category":
                            cat = (st.str_value
                                   or st_meta.get(st.ref_value).name
                                   if st.ref_value else st.str_value)
                    buckets[classify(name, cat or "")] += dur
                    busy += dur
                    t_start = ev.offset_ps / 1e6
                    wall_lo = t_start if wall_lo is None else min(
                        wall_lo, t_start)
                    wall_hi = max(wall_hi, t_start + dur)
    wall = (wall_hi - (wall_lo or 0.0))
    return dict(buckets), busy, wall


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--outdir", default="")
    args, extra = ap.parse_known_args(argv)
    if args.quick:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    cfg = (QUICK if args.quick else FLAGSHIP) + extra
    outdir = args.outdir or tempfile.mkdtemp(prefix="tpu_profile_")
    dt = capture(cfg, args.steps, outdir)
    buckets, busy, wall = parse_xplanes(outdir)
    per_step = {k: v / args.steps / 1e3 for k, v in buckets.items()}  # ms
    report = {
        "config": " ".join(cfg),
        "measured_step_ms": round(dt * 1e3, 1),
        "device_busy_ms_per_step": round(busy / args.steps / 1e3, 1),
        "device_idle_ms_per_step": round(
            max(0.0, wall - busy) / args.steps / 1e3, 1),
        "breakdown_ms_per_step": {
            k: round(v, 1) for k, v in sorted(
                per_step.items(), key=lambda kv: -kv[1])},
        "breakdown_pct_of_busy": {
            k: round(100 * v * args.steps * 1e3 / busy, 1)
            for k, v in sorted(per_step.items(), key=lambda kv: -kv[1])},
        "trace_dir": outdir,
    }
    print(json.dumps(report, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
