#!/usr/bin/env python3
"""Profile-derived step-time breakdown for a payload config.

Answers the question the bench suite's MFU numbers raise but cannot
answer: WHERE does the non-MXU time go? Captures a ``jax.profiler`` device
trace of a few steady-state steps, parses the XPlane protobuf directly
(tensorboard_plugin_profile ships the schema; no TensorBoard UI needed),
and aggregates per-op self time by the TPU runtime's ``hlo_category`` stat
(schema: tensorflow/tsl's xplane_pb2, shipped in the baked image) —
convolution/dot fusions (MXU), the Pallas attention custom-calls,
elementwise/reduce fusions (optimizer + remat recompute), infeed/outfeed,
and idle gaps (host stall) from busy-vs-wall time.

Default config = the flagship GQA bench row, so the output slots straight
into docs/benchmarks.md's attribution table:

    python hack/profile_breakdown.py            # flagship GQA, 6 steps
    python hack/profile_breakdown.py --quick    # tiny CPU smoke
"""

from __future__ import annotations

import argparse
import collections
import glob
import itertools
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FLAGSHIP = ["--dim", "2048", "--layers", "8", "--heads", "16",
            "--kv-heads", "4", "--batch", "32", "--seq-len", "2048",
            "--vocab", "32768", "--remat", "--remat-policy", "dots",
            "--grad-accum", "4", "--adam-mu-dtype", "bf16"]
QUICK = ["--dim", "64", "--layers", "2", "--heads", "2", "--batch", "4",
         "--seq-len", "128", "--vocab", "256"]


def capture(argv, steps: int, outdir: str,
            payload: str = "transformer") -> float:
    """Run warmup + ``steps`` traced steps; returns measured sec/step.
    ``payload`` selects the LM payload module (transformer / moe /
    pipeline) so MoE dispatch and pipeline tick schedules get the same
    attribution treatment as the flagship."""
    import importlib

    import jax
    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import data as data_mod, transformer

    module = importlib.import_module(f"tpu_operator.payload.{payload}")
    targs = module.parse_args(argv)
    mesh, _m, state, step, batches = module.build(targs)
    spec = (transformer.lm_token_spec(mesh)
            if payload == "transformer" else P("data", None))
    pregen = [data_mod.put_global_batch(mesh, *b, spec=spec)
              for b in itertools.islice(batches, 4)]
    cycled = itertools.cycle(pregen)
    for _ in range(3):
        state, metrics = step(state, *next(cycled))
    jax.device_get(metrics["loss"])

    jax.profiler.start_trace(outdir)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, *next(cycled))
    jax.device_get(metrics["loss"])
    dt = (time.perf_counter() - t0) / steps
    jax.profiler.stop_trace()
    return dt


OVERLAPPED = "dma / async (overlapped, not counted as busy)"


def classify(name: str, category: str) -> str:
    """hlo_category → report bucket. The category ALONE decides whenever
    present: HLO instruction names are full printed instructions whose
    operand references leak other ops' names (a conv fusion consuming a
    custom-call's output contains 'custom-call' in its text — a name
    heuristic misbucketed 377 ms/step of flagship matmuls as attention).
    TPU categories observed: 'convolution fusion' (dots lower to these),
    'loop fusion'/'non-fusion elementwise'/'reduce', 'custom-call'/'custom
    fusion' (pallas), 'async-start/done' + 'copy-start/done' (DMA spans
    that run CONCURRENTLY with compute — counting them as busy
    double-counts the step, so they bucket separately and are excluded
    from busy time)."""
    cat = (category or "").lower()
    if "async" in cat or cat.startswith("copy-"):
        return OVERLAPPED
    if "custom" in cat:
        return "attention kernels (pallas custom-calls)"
    if "convolution" in cat or cat.startswith("dot") or "matmul" in cat \
            or "output fusion" in cat:
        return "matmul (MXU)"
    if "all-reduce" in cat or "all-gather" in cat or "collective" in cat \
            or "permute" in cat:
        return "collectives"
    if "infeed" in cat or "outfeed" in cat or "data formatting" in cat \
            or "host" in cat:
        return "data movement"
    if not cat:  # no category metadata: fall back to name sniffing
        low = name.split("=", 1)[0].lower()
        if "custom-call" in low or "pallas" in low:
            return "attention kernels (pallas custom-calls)"
    return "elementwise / reduce / other fusions"


def parse_xplanes(outdir: str):
    """{bucket: total_self_us}, device_busy_us, plane_wall_us from every
    TPU device plane under outdir."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise SystemExit(f"no xplane.pb under {outdir}")
    buckets: dict = collections.defaultdict(float)
    busy = 0.0
    wall_lo, wall_hi = None, 0.0
    for path in paths:
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        for plane in xs.planes:
            if "TPU" not in plane.name:
                continue
            ev_meta = plane.event_metadata
            st_meta = plane.stat_metadata

            # hlo_category lives in the event *metadata* stats (per unique
            # HLO op), not the per-occurrence event stats.
            def meta_category(mid: int) -> str:
                meta = ev_meta.get(mid)
                if meta is None:
                    return ""
                for st in meta.stats:
                    key = st_meta.get(st.metadata_id)
                    if key is not None and key.name == "hlo_category":
                        if st.str_value:
                            return st.str_value
                        ref = st_meta.get(st.ref_value)
                        return ref.name if ref is not None else ""
                return ""

            cat_cache: dict = {}
            for line in plane.lines:
                if line.name != "XLA Ops":
                    continue
                # Control-flow HLOs (the grad-accum `while`, conditionals)
                # are recorded as events SPANNING their body ops, so a
                # naive sum double-counts every looped op. Containment
                # sweep → per-event *self* time: each child's duration is
                # subtracted from its innermost enclosing parent.
                evs = sorted(
                    ((e.offset_ps, e.offset_ps + e.duration_ps,
                      e.metadata_id) for e in line.events),
                    key=lambda e: (e[0], -(e[1] - e[0])))
                selfs = []
                stack = []  # indices into selfs of currently-open events
                for s, t, mid in evs:
                    while stack and s >= selfs[stack[-1]][1]:
                        stack.pop()
                    selfs.append([s, t, mid, (t - s)])
                    if stack:
                        selfs[stack[-1]][3] -= (t - s)
                    stack.append(len(selfs) - 1)
                for s, t, mid, self_ps in selfs:
                    dur = max(0, self_ps) / 1e6  # ps → us
                    if mid not in cat_cache:
                        meta = ev_meta.get(mid)
                        cat_cache[mid] = classify(
                            meta.name if meta else "",
                            meta_category(mid))
                    bucket = cat_cache[mid]
                    buckets[bucket] += dur
                    if bucket == OVERLAPPED:
                        continue  # concurrent DMA: not device busy time
                    busy += dur
                    wall_lo = (s / 1e6 if wall_lo is None
                               else min(wall_lo, s / 1e6))
                    wall_hi = max(wall_hi, t / 1e6)
    wall = (wall_hi - (wall_lo or 0.0))
    return dict(buckets), busy, wall


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--outdir", default="")
    ap.add_argument("--parse-only", action="store_true",
                    help="re-analyze an existing --outdir trace without "
                         "re-capturing (iterate on bucketing for free)")
    ap.add_argument("--bare", action="store_true",
                    help="do not prepend the FLAGSHIP/QUICK defaults — "
                         "the extra argv IS the whole config (required "
                         "for store_true flags like --remat, which the "
                         "defaults could otherwise force on)")
    ap.add_argument("--payload",
                    choices=("transformer", "moe", "pipeline"),
                    default="transformer",
                    help="which LM payload to profile (extra argv go to "
                         "its parser; the FLAGSHIP defaults apply only to "
                         "transformer)")
    args, extra = ap.parse_known_args(argv)
    if args.quick:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.payload != "transformer" or args.bare:
        cfg = extra
    else:
        cfg = (QUICK if args.quick else FLAGSHIP) + extra
    outdir = args.outdir or tempfile.mkdtemp(prefix="tpu_profile_")
    dt = None
    if not args.parse_only:
        dt = capture(cfg, args.steps, outdir, payload=args.payload)
    buckets, busy, wall = parse_xplanes(outdir)
    overlapped = buckets.pop(OVERLAPPED, 0.0)
    per_step = {k: v / args.steps / 1e3 for k, v in buckets.items()}  # ms
    report = {
        "config": " ".join(cfg),
        "measured_step_ms": round(dt * 1e3, 1) if dt is not None else None,
        "device_busy_ms_per_step": round(busy / args.steps / 1e3, 1),
        "device_idle_ms_per_step": round(
            max(0.0, wall - busy) / args.steps / 1e3, 1),
        "overlapped_dma_ms_per_step": round(
            overlapped / args.steps / 1e3, 1),
        "breakdown_ms_per_step": {
            k: round(v, 1) for k, v in sorted(
                per_step.items(), key=lambda kv: -kv[1])},
        "breakdown_pct_of_busy": {
            k: round(100 * v * args.steps * 1e3 / busy, 1)
            for k, v in sorted(per_step.items(), key=lambda kv: -kv[1])},
        "trace_dir": outdir,
    }
    print(json.dumps(report, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
