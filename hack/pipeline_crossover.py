#!/usr/bin/env python3
"""Project WHERE interleaved 1F1B beats plain 1F1B on a real mesh.

One attached chip cannot time a multi-stage pipeline, but it can measure
the two things a projection needs: the per-tick machinery cost of the
schedule runtime (buffer ops, cond dispatch, permutes — the S=1 rows) and
the dense compute the schedule portions out. This tool drives the SAME
schedule generators the runtime executes (pipeline.onef1b_schedule /
onef1b_interleaved_schedule) with a per-tick cost model calibrated on
those measurements, and prints the projected step time and the
plain-vs-interleaved crossover over an (S, M, V) grid.

Model (per data shard, weak scaling — per-device batch fixed):
  u_f   = D / (3·S·V·M)     fwd of one chunk on one microbatch
  u_b   = 2·u_f             bwd of the same
  B-tick work = u_b + rho·u_f   (input-stash recompute of the chunk fwd;
                                 rho < 1 because the dots remat policy
                                 keeps matmul outputs)
  tick cost = max over devices of the fired unit's work + m(M)
  m(M) = m0 · M0/M          per-tick machinery, proportional to the
                            microbatch activation footprint

Calibration solves (rho, m0) exactly from the two measured S=1 rows
(plain and interleaved V=2 share D and rho; the interleaved row has 2x
the ticks), then VALIDATES by reproducing both measurements to <0.1 ms
by construction. Defaults below are the round-5 bench numbers
(d1024 L8 batch16 T2048, BENCH_SUITE.json): D=327.4, plain 393.8,
interleaved 418.0 at M0=4 — giving rho=0.387 (consistent with the
round-4 profile attribution of ~40 ms recompute) and m0=3.0 ms.

Caveats the projection states rather than hides: machinery is assumed
activation-proportional (holds for the measured buffer/select ops, not
for the fixed cond/table costs, which are small); ppermute hop latency on
a real mesh is taken as overlapped with compute (neighbor ICI transfers
of one microbatch activation behind a chunk's compute); embed/head
imbalance on first/last stages is ignored (both schedules pay it
equally).

Usage:
  python hack/pipeline_crossover.py                    # default grid
  python hack/pipeline_crossover.py --dense-ms 327.4 \
      --plain-ms 393.8 --interleaved-ms 418.0 --m0-batch 4
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def calibrate(dense_ms: float, plain_ms: float, inter_ms: float,
              m_cal: int):
    """(rho, m0) from the two S=1 rows at M0=m_cal microbatches.

    S=1 plain:        D + rho·(D/3) + 2·M0·m0 = plain_ms
    S=1 interleaved:  D + rho·(D/3) + 4·M0·m0 = inter_ms
    (V=2 halves every unit but doubles the unit count — compute is
    invariant; only the tick count changes.)"""
    m0 = (inter_ms - plain_ms) / (2 * m_cal)
    rho = (plain_ms - dense_ms - 2 * m_cal * m0) / (dense_ms / 3)
    return rho, m0


def simulate(kind: str, s: int, v: int, m: int, dense_ms: float,
             rho: float, m0: float, m_cal: int) -> float:
    """Projected step ms for one data shard of the given pipeline."""
    from tpu_operator.payload import pipeline

    u_f = dense_ms / (3 * s * v * m)
    u_b = 2 * u_f + rho * u_f
    m_tick = m0 * m_cal / m

    if kind == "plain":
        assert v == 1
        table = pipeline.onef1b_schedule(s, m)
        rows = [[None if u is None else u[0] for u in row] for row in table]
    else:
        tbl = pipeline.onef1b_interleaved_schedule(s, v, m)
        act = tbl["act"]
        rows = [["F" if a == 1 else ("B" if a == 2 else None)
                 for a in row] for row in act]

    wall = 0.0
    for row in rows:
        work = max((u_f if u == "F" else u_b) if u else 0.0 for u in row)
        wall += work + m_tick
    return wall


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dense-ms", type=float, default=327.4)
    ap.add_argument("--plain-ms", type=float, default=393.8)
    ap.add_argument("--interleaved-ms", type=float, default=418.0)
    ap.add_argument("--m0-batch", type=int, default=4,
                    help="microbatch count the S=1 rows were measured at")
    ap.add_argument("--stages", type=int, nargs="*",
                    default=[2, 4, 8, 16])
    ap.add_argument("--virtual", type=int, nargs="*", default=[2, 4])
    args = ap.parse_args(argv)

    rho, m0 = calibrate(args.dense_ms, args.plain_ms,
                        args.interleaved_ms, args.m0_batch)
    print(f"calibrated: rho={rho:.3f} (recompute fraction of chunk fwd), "
          f"m0={m0:.2f} ms/tick at M={args.m0_batch}")
    for check, kind, v in (("plain", "plain", 1),
                           ("interleaved", "interleaved", 2)):
        got = simulate(kind, 1, v, args.m0_batch, args.dense_ms, rho, m0,
                       args.m0_batch)
        want = args.plain_ms if check == "plain" else args.interleaved_ms
        print(f"  S=1 {check:12s} reproduce: {got:7.1f} ms "
              f"(measured {want:.1f})")

    print(f"\n{'S':>3} {'M':>4} | {'plain':>8} | "
          + " | ".join(f"V={v:<2}     " for v in args.virtual)
          + " | winner")
    for s in args.stages:
        for mult in (1, 2, 4, 8):
            m = s * mult
            plain = simulate("plain", s, 1, m, args.dense_ms, rho, m0,
                             args.m0_batch)
            row = [f"{s:>3} {m:>4} | {plain:7.1f}ms |"]
            best, best_ms = "plain", plain
            for v in args.virtual:
                try:
                    t = simulate("interleaved", s, v, m, args.dense_ms,
                                 rho, m0, args.m0_batch)
                    row.append(f" {t:7.1f}ms |")
                    if t < best_ms:
                        best, best_ms = f"V={v}", t
                except Exception:
                    row.append("       -- |")
            gain = 100 * (plain / best_ms - 1)
            row.append(f" {best}" + (f" (+{gain:.0f}%)" if best != "plain"
                                     else ""))
            print("".join(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
