#!/usr/bin/env python3
"""Contract-analysis gate: run the tpu_operator/analysis rule suite.

Usage:
    python hack/analyze.py                 # all rules, repo root
    python hack/analyze.py --rules env-contract,exceptions
    python hack/analyze.py --root /some/tree --allowlist /dev/null
    python hack/analyze.py --list-rules
    python hack/analyze.py -v              # also show suppressed findings

Exit status: 0 when clean; 1 on any unsuppressed finding OR any stale
allowlist entry (a suppression matching nothing must be deleted — it
would otherwise hide a future regression of something already fixed).

Run from hack/verify.sh before the test pyramid: these checks are cheaper
than any test and catch the cross-file drift tests structurally cannot
(a spec field added to types.py with no schema entry breaks no unit test —
it breaks users).
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpu_operator.analysis.driver import RULES, run_analysis  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--root", default=REPO,
                   help="tree to analyze (default: this repo)")
    p.add_argument("--rules", default="",
                   help="comma-separated rule ids (default: all)")
    p.add_argument("--allowlist", default=None,
                   help="allowlist file (default: "
                        "<root>/hack/analyze_allowlist.txt)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also print allowlist-suppressed findings")
    args = p.parse_args()

    if args.list_rules:
        for rule_id, mod in RULES.items():
            first = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{rule_id:16s} {first}")
        return 0

    rules = [r.strip() for r in args.rules.split(",") if r.strip()] or None
    allowlist = Path(args.allowlist) if args.allowlist else None
    try:
        active, suppressed, stale = run_analysis(
            Path(args.root), rules=rules, allowlist_path=allowlist)
    except ValueError as e:
        print(f"analyze: {e}", file=sys.stderr)
        return 2

    if args.verbose and suppressed:
        print(f"analyze: {len(suppressed)} finding(s) suppressed by "
              f"allowlist:")
        for f in suppressed:
            print(f"  [suppressed] {f.render()}")
    failed = False
    if active:
        failed = True
        print(f"analyze: FAIL — {len(active)} finding(s):")
        for f in active:
            print(f"  {f.render()}")
    if stale:
        failed = True
        print(f"analyze: FAIL — {len(stale)} stale allowlist entr"
              f"{'y' if len(stale) == 1 else 'ies'} (matched nothing; "
              f"delete them):")
        for rule, key in sorted(stale):
            print(f"  {rule}  {key}")
    if failed:
        return 1
    ran = rules or list(RULES)
    print(f"analyze: OK ({len(ran)} rules, "
          f"{len(suppressed)} allowlisted finding(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
