#!/usr/bin/env bash
# Run the operator locally against the current kubeconfig context —
# the reference's developer loop (developer_guide.md:103-129: build the
# binary, run it outside the cluster, kubectl create the example job).
#
# Usage: hack/run-local.sh [extra operator flags...]
set -euo pipefail

# Kubeconfig resolution ($KUBECONFIG → ~/.kube/config → in-cluster) is
# handled by the operator itself (util/k8sutil.get_cluster_config).
cd "$(dirname "$0")/.."
exec python -m tpu_operator.cmd.main --no-leader-elect "$@"
